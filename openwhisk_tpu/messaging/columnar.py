"""Columnar batch wire records: one encoded frame for a whole micro-batch.

ISSUE 12's tentpole: the activation BATCH — not the activation — is the
unit of work on every host hop. The coalescing producer already ships one
`pubN` frame per micro-batch, but each sub-message inside it is still an
independently-JSON-encoded ActivationMessage / ack: at 1,000 activations/s
the host pays ~N `json.dumps` + N `json.loads` per hop, plus N parses of
the SAME identity/action/controller sub-objects (the host observatory
measured the serde plane at ~7.7% of wall per hop at 512/s, before
counting the per-message object construction it feeds).

This module is the wire half of the columnar hot path:

  * `ActivationBatchMessage` — N controller->invoker dispatches packed as
    ONE struct-of-arrays JSON record: per-batch dedup tables for the
    repeated heavy sub-objects (users, (action, revision) pairs,
    controller ids) and packed per-row columns (activation ids, user /
    action indices, transids, blocking bits, arg payloads — the arg
    column is the "one blob" of the packed form: a single `json.dumps`
    writes every row's args in one C-speed pass, and sparse columns
    carry the rarely-present fields). ONE serialize per batch; the
    decode side rebuilds N `ActivationMessage`s parsing each unique
    identity/action exactly once.
  * `AckBatchMessage` — the mirror record for the invoker->controller
    completion fan-in (kinds, transids, ids, invoker dedup, system-error
    bits, response payloads).
  * `is_batch_payload` / `batch_hop_of` — frame sniffing for consumers:
    every batch payload starts with the `{"whiskBatch":` magic, so a
    feed handler can route a frame to the batch decode without parsing
    it (plain per-message frames never start with that key — neither
    ActivationMessage nor the acks serialize a `whiskBatch` field
    first, and json.dumps key order is insertion order).

Off switch: the batch wire rides the coalescing producer
(`CONFIG_whisk_bus_coalesce_batchWire=false` restores one independently
encoded payload per message — the serial wire format, byte-exact).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ..core.entity import ActivationId, ControllerInstanceId, Identity
from ..core.entity.names import FullyQualifiedEntityName
from ..utils.transaction import TransactionId
from .message import (AcknowledgementMessage, ActivationMessage,
                      CombinedCompletionAndResultMessage, CompletionMessage,
                      Message, ResultMessage)

#: every batch payload leads with this key (json.dumps preserves insertion
#: order, so the magic is a stable byte prefix — the cheap routing test)
BATCH_MAGIC = b'{"whiskBatch":'

KIND_ACTIVATION = "act1"
KIND_ACK = "ack1"

#: serde hop labels by batch kind (mirrors connector._SERDE_HOPS so the
#: host observatory's per-hop accounting survives the batch wire)
_BATCH_HOPS = {KIND_ACTIVATION: "activation", KIND_ACK: "completion_ack"}


def is_batch_payload(raw) -> bool:
    """True when `raw` is a batch wire record (magic-prefix sniff; no
    parse). Accepts bytes/bytearray/str."""
    if isinstance(raw, str):
        return raw.startswith('{"whiskBatch":')
    return bytes(raw[:len(BATCH_MAGIC)]) == BATCH_MAGIC


def batch_hop_of(kind: str) -> str:
    return _BATCH_HOPS.get(kind, "other")


def batchable_family(msg) -> Optional[str]:
    """The batch family a message coalesces into, or None for messages
    that stay per-frame (pings, events: background chatter whose framing
    is not on the hot path)."""
    if isinstance(msg, ActivationMessage):
        return KIND_ACTIVATION
    if isinstance(msg, AcknowledgementMessage):
        return KIND_ACK
    return None


class _Dedup:
    """Insertion-ordered dedup table: intern() returns the index of the
    (hashable) key, appending `value` on first sight."""

    __slots__ = ("index", "values")

    def __init__(self):
        self.index: Dict[object, int] = {}
        self.values: List[object] = []

    def intern(self, key, value) -> int:
        i = self.index.get(key)
        if i is None:
            i = len(self.values)
            self.index[key] = i
            self.values.append(value)
        return i


class ActivationBatchMessage(Message):
    """N ActivationMessages as one columnar wire record (see module doc).

    The struct-of-arrays layout: `users`/`actions`/`ctrls` are per-batch
    dedup tables (each unique identity / (fqn, revision) / controller
    encoded ONCE); `ids`, `u`, `a`, `c`, `tx`, `bl`, `args` are
    length-N columns; `cause`/`trace`/`init` are sparse {row: value}
    columns present only when some row carries the field. `fence` is the
    batch-level HA epoch (one controller's flush shares one epoch; a
    rare mixed-epoch flush falls back to a sparse per-row column)."""

    def __init__(self, msgs: List[ActivationMessage]):
        self.msgs = msgs

    #: the waterfall produce edge stamps per activation: connector
    #: stamp_produce reads this instead of .activation_id
    @property
    def activation_ids(self) -> List[str]:
        return [m.activation_id.asString for m in self.msgs]

    def to_json(self) -> dict:
        users, actions, ctrls = _Dedup(), _Dedup(), _Dedup()
        ids: List[str] = []
        u_col: List[int] = []
        a_col: List[int] = []
        c_col: List[int] = []
        tx_col: List[object] = []
        bl_col: List[int] = []
        args_col: List[Optional[dict]] = []
        cause: Dict[str, str] = {}
        trace: Dict[str, dict] = {}
        init: Dict[str, dict] = {}
        fences: Dict[str, int] = {}
        for row, m in enumerate(self.msgs):
            ids.append(m.activation_id.asString)
            # identity dedup keys on the subject+namespace-uuid pair (the
            # stable identity key); the action table keys on (fqn, rev)
            ident = m.user
            u_col.append(users.intern(
                (ident.subject, ident.namespace.uuid.asString),
                ident.to_json()))
            a_col.append(actions.intern((str(m.action), m.revision),
                                        [str(m.action), m.revision]))
            c_col.append(ctrls.intern(m.root_controller_index.name,
                                      m.root_controller_index.name))
            tx_col.append(m.transid.to_json())
            bl_col.append(1 if m.blocking else 0)
            args_col.append(m.content)
            if m.cause is not None:
                cause[str(row)] = m.cause.to_json()
            if m.trace_context is not None:
                trace[str(row)] = m.trace_context
            if m.init_args:
                init[str(row)] = m.init_args
            if m.fence_epoch is not None:
                fences[str(row)] = m.fence_epoch
        out = {
            "whiskBatch": KIND_ACTIVATION,
            "users": users.values,
            "actions": actions.values,
            "ctrls": ctrls.values,
            "ids": ids,
            "u": u_col, "a": a_col, "c": c_col,
            "tx": tx_col, "bl": bl_col,
            "args": args_col,
        }
        if cause:
            out["cause"] = cause
        if trace:
            out["trace"] = trace
        if init:
            out["init"] = init
        if fences:
            # the common case is one shared epoch: collapse to a scalar
            vals = set(fences.values())
            if len(vals) == 1 and len(fences) == len(self.msgs):
                out["fence"] = vals.pop()
            else:
                out["fences"] = fences
        return out

    @staticmethod
    def parse(raw) -> List[ActivationMessage]:
        """One json.loads + shared-subobject reconstruction: each unique
        identity/action/controller in the batch is parsed exactly once
        and the rebuilt objects are SHARED across the batch's messages
        (read-only on the consume side, like the reference's case
        classes)."""
        j = json.loads(raw)
        return ActivationBatchMessage.from_json(j)

    @staticmethod
    def from_json(j: dict) -> List[ActivationMessage]:
        users = [Identity.from_json(u) for u in j["users"]]
        actions = [(FullyQualifiedEntityName.parse(a), rev)
                   for a, rev in j["actions"]]
        ctrls = [ControllerInstanceId(c) for c in j["ctrls"]]
        cause = j.get("cause") or {}
        trace = j.get("trace") or {}
        init = j.get("init") or {}
        fence = j.get("fence")
        fences = j.get("fences") or {}
        out: List[ActivationMessage] = []
        for row, (aid, u, a, c, tx, bl, args) in enumerate(zip(
                j["ids"], j["u"], j["a"], j["c"], j["tx"], j["bl"],
                j["args"])):
            key = str(row)
            fqn, rev = actions[a]
            row_cause = cause.get(key)
            out.append(ActivationMessage(
                TransactionId.from_json(tx), fqn, rev, users[u],
                ActivationId(aid), ctrls[c], bool(bl), args,
                init.get(key) or {},
                ActivationId(row_cause) if row_cause else None,
                trace.get(key),
                fence if fence is not None else fences.get(key)))
        return out


#: ack kind -> wire code (one char per row in the kinds column)
_ACK_CODES = {"completion": "c", "result": "r", "combined": "b"}
_ACK_KINDS = {v: k for k, v in _ACK_CODES.items()}


class AckBatchMessage(Message):
    """N invoker->controller acks as one columnar wire record. The heavy
    per-row payload (the WhiskActivation response) stays per-row — it IS
    the data — but the batch pays ONE json.dumps/loads for all of them,
    and the invoker table dedups the repeated instance id."""

    def __init__(self, msgs: List[AcknowledgementMessage]):
        self.msgs = msgs

    @property
    def activation_ids(self) -> List[str]:
        return [m.activation_id.asString for m in self.msgs]

    def to_json(self) -> dict:
        invs = _Dedup()
        kinds: List[str] = []
        tx_col: List[object] = []
        ids: List[str] = []
        iv_col: List[int] = []
        err_col: List[int] = []
        resp_col: List[Optional[dict]] = []
        for m in self.msgs:
            kinds.append(_ACK_CODES.get(m.kind, "b"))
            tx_col.append(m.transid.to_json())
            ids.append(m.activation_id.asString)
            iv_col.append(-1 if m.invoker is None
                          else invs.intern(m.invoker.as_string,
                                           m.invoker.to_json()))
            err_col.append(1 if m.is_system_error else 0)
            resp_col.append(m.activation.to_json()
                            if m.activation is not None else None)
        return {
            "whiskBatch": KIND_ACK,
            "invs": invs.values,
            "kinds": "".join(kinds),
            "tx": tx_col, "ids": ids, "iv": iv_col, "err": err_col,
            "resp": resp_col,
        }

    @staticmethod
    def parse(raw) -> List[AcknowledgementMessage]:
        j = json.loads(raw)
        return AckBatchMessage.from_json(j)

    @staticmethod
    def from_json(j: dict) -> List[AcknowledgementMessage]:
        from ..core.entity import InvokerInstanceId, WhiskActivation
        invs = [InvokerInstanceId.from_json(v) for v in j["invs"]]
        out: List[AcknowledgementMessage] = []
        for code, tx, aid, iv, err, resp in zip(
                j["kinds"], j["tx"], j["ids"], j["iv"], j["err"],
                j["resp"]):
            transid = TransactionId.from_json(tx)
            inv = invs[iv] if iv >= 0 else None
            act = WhiskActivation.from_json(resp) if resp else None
            kind = _ACK_KINDS.get(code, "combined")
            if kind == "completion":
                out.append(CompletionMessage(transid, ActivationId(aid),
                                             bool(err), inv))
            elif kind == "result":
                out.append(ResultMessage(transid, act))
            else:
                out.append(CombinedCompletionAndResultMessage(transid, act,
                                                              inv))
        return out


def make_batch(family: str, msgs: list) -> Message:
    """Wrap same-family messages into their batch record (the
    `serialize_many` entry point the coalescing producer uses)."""
    if family == KIND_ACTIVATION:
        return ActivationBatchMessage(msgs)
    if family == KIND_ACK:
        return AckBatchMessage(msgs)
    raise ValueError(f"not a batchable family: {family!r}")


def parse_batch(raw) -> Tuple[str, list]:
    """Decode one batch payload -> (kind, [messages]). The caller sniffs
    with is_batch_payload first; an unknown kind raises ValueError (the
    feed's corrupt-message posture)."""
    j = json.loads(raw)
    kind = j.get("whiskBatch")
    if kind == KIND_ACTIVATION:
        return kind, ActivationBatchMessage.from_json(j)
    if kind == KIND_ACK:
        return kind, AckBatchMessage.from_json(j)
    raise ValueError(f"unknown batch kind {kind!r}")

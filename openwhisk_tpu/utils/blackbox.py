"""Incident forensics observatory: alert-triggered black-box capture.

Nine observability planes answer "what is happening" — flight recorder,
telemetry/SLO, anomaly/alerts, waterfall, host observatory, placement
quality, tail traces, the fleet federation, the event log — but every one
of them is a live ring: by the time an operator queries `/admin/*` after
an SLO burn or a partition failover, the evidence has aged out, and
nothing ever JOINS the planes around one event. This module is the
flight-data-recorder answer (ISSUE 19): when an alert fires (or a
structural distress event lands in the EventLog), freeze a cross-plane
forensic bundle to disk — automatically, exactly once per incident.

Triggers
  * AlertEngine FSM transitions into `firing` (AlertEngine.listeners) —
    stragglers, error/timeout spikes, SLO burn, recompile churn,
    journal stall all arrive through this one choke point.
  * Structural distress events already in GLOBAL_EVENT_LOG:
    `journal_stall`, `part_superseded`, `spill_burst` directly, and
    `fence_discard` as a burst (>= `fence_burst_n` discards within
    `fence_burst_window_s` — a single late frame after a clean handoff
    is routine, a burst is a fencing incident).
  * A debounce window (`debounce_s`) coalesces the storm: the straggler
    alert, its SLO-burn cousin and the spillover burst they cause are ONE
    incident and produce ONE bundle (`coalesced` counts the suppressed
    triggers, stamped into the bundle on the way out).

The bundle (one CRC-framed, versioned file per incident; bounded
retention ring of `retention` files):
  * trigger context + the alert transition log + active alerts,
  * the anomaly score matrix with evidence,
  * telemetry SLO report (burn rates, windows),
  * waterfall percentiles + slowest exemplar rows,
  * flight-recorder recent ring with decisions + quality digests,
  * host-observatory snapshot (+ a bounded profiler capture when
    `profiler_capture_s` > 0),
  * every kept trace overlapping the window,
  * the EventLog timeline slice,
  * the journal seq window (mark -> now) WITH the records themselves, so
    the bundle replays standalone via tools/owdebug.py even after the
    journal prunes, and the balancer books captured at freeze time — the
    time-travel debugger diffs re-derived state against them and replay
    divergence becomes incident evidence.

Threading: triggers arrive on the event loop (alert evaluation tick) or
arbitrary threads (EventLog taps); the capture itself runs on a dedicated
daemon worker thread, so the device syncs some plane reads imply NEVER
happen on the event loop. The two reads that must run on the loop — the
balancer's `snapshot_parts()` (journal-seq-consistent books) and arming
the host profiler capture — are marshalled back via the loop handle
stashed at trigger time; everything else (telemetry/anomaly device pulls,
journal file reads, the bundle write) stays on the worker. Every plane
read is individually guarded: a broken plane yields an `error` entry in
the bundle, never a lost incident.

Off-switch: `CONFIG_whisk_incidents_enabled` defaults to **False** —
unlike the read-only planes this one writes files on trigger, so it is
explicitly armed per deployment (the runbook's first step). Disabled,
`install()` refuses (GLOBAL_HOST_OBSERVATORY pattern), no listener
attaches, no thread starts, no family renders, and the admin endpoints
404.
"""
from __future__ import annotations

import dataclasses
import json
import os
import queue
import struct
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .config import load_config
from .eventlog import GLOBAL_EVENT_LOG, identity

#: bundle frame: magic | u32 payload len | u32 crc32(payload) | payload
#: (the journal's torn/corrupt-tolerant framing, one frame per file)
BUNDLE_MAGIC = b"WBB1"
#: bumped on any payload schema change; readers refuse newer majors
BUNDLE_VERSION = 1

#: EventLog kinds that are themselves incidents (one record = trigger)
DISTRESS_KINDS = frozenset({"journal_stall", "part_superseded",
                            "spill_burst"})


@dataclasses.dataclass(frozen=True)
class IncidentConfig:
    """`CONFIG_whisk_incidents_*` env overrides (config.py convention)."""

    #: master switch. Default OFF: this plane writes disk bundles on
    #: trigger — it is armed per deployment, not ambient (module doc).
    enabled: bool = False
    #: bundle directory ("" = `<tmp>/whisk-incidents-<pid>`)
    directory: str = ""
    #: retention ring: newest N bundles kept, older pruned after a write
    retention: int = 16
    #: one bundle per storm: triggers within this window coalesce
    debounce_s: float = 30.0
    #: evidence look-back: traces/events older than this are out of scope
    window_s: float = 120.0
    #: bounded host-profiler capture folded into the bundle (0 = skip —
    #: the capture holds the worker for its full duration)
    profiler_capture_s: float = 0.0
    #: flight-recorder batches frozen into the bundle
    recent_batches: int = 64
    #: EventLog records frozen into the bundle (window-filtered)
    recent_events: int = 256
    #: kept traces frozen into the bundle (newest overlapping first)
    recent_traces: int = 16
    #: journal records embedded (newest window records; a bundle must
    #: stay a bundle, not a journal mirror)
    max_journal_records: int = 4096
    #: fence_discard burst trigger: >= n discards within window_s seconds
    fence_burst_n: int = 8
    fence_burst_window_s: float = 5.0


def incidents_config(data: Optional[dict] = None) -> IncidentConfig:
    return load_config(IncidentConfig, data, env_path="incidents")


# -- bundle file format ----------------------------------------------------
def write_bundle(path: str, payload: dict) -> int:
    """Serialize + CRC-frame `payload` to `path` atomically (tmp +
    os.replace — a crashed capture never leaves a torn bundle behind).
    Returns the byte size written."""
    body = json.dumps(payload, separators=(",", ":"),
                      default=str).encode("utf-8")
    frame = (BUNDLE_MAGIC + struct.pack("<II", len(body),
                                        zlib.crc32(body) & 0xFFFFFFFF)
             + body)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(frame)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(frame)


def read_bundle(path: str) -> Optional[dict]:
    """Parse one bundle file. Returns None (never raises) on a missing,
    torn, corrupt or future-versioned file — forensic reads must degrade,
    not 500."""
    try:
        with open(path, "rb") as f:
            head = f.read(len(BUNDLE_MAGIC) + 8)
            if (len(head) != len(BUNDLE_MAGIC) + 8
                    or head[:len(BUNDLE_MAGIC)] != BUNDLE_MAGIC):
                return None
            length, crc = struct.unpack("<II", head[len(BUNDLE_MAGIC):])
            body = f.read(length)
        if len(body) != length or (zlib.crc32(body) & 0xFFFFFFFF) != crc:
            return None
        payload = json.loads(body.decode("utf-8"))
        if int(payload.get("version", 0)) > BUNDLE_VERSION:
            return None
        return payload
    except (OSError, ValueError):
        return None


def _summary(payload: dict) -> dict:
    """The `/admin/incidents` row: everything an operator needs to pick a
    bundle, nothing heavy."""
    planes = payload.get("planes") or {}
    j = planes.get("journal") or {}
    return {
        "id": payload.get("id"),
        "ts": payload.get("ts"),
        "reason": payload.get("reason"),
        "severity": payload.get("severity"),
        "labels": payload.get("labels") or {},
        "coalesced": payload.get("coalesced", 0),
        # only planes that actually landed: a None value means the grab
        # failed (its error is in plane_errors) and must not read as
        # captured from the list row
        "planes": sorted(k for k, v in planes.items() if v is not None),
        "plane_errors": payload.get("plane_errors") or {},
        "journal_from_seq": j.get("from_seq"),
        "journal_to_seq": j.get("to_seq"),
        "journal_records": len(j.get("records") or ()),
        "activation_ids": len(payload.get("activation_ids") or ()),
        "instance": (payload.get("identity") or {}).get("instance"),
    }


class IncidentRecorder:
    """Alert-triggered cross-plane black-box capture (module doc)."""

    def __init__(self, config: Optional[IncidentConfig] = None, logger=None):
        #: env-built recorders re-read `CONFIG_whisk_incidents_*` at every
        #: un-owned install(): the plane is armed per deployment, and the
        #: process-global instance predates any test/bench env override
        self._from_env = config is None
        self.config = config or incidents_config()
        self.logger = logger
        self.enabled = bool(self.config.enabled)
        self._lock = threading.Lock()
        self._owner: Optional[object] = None
        self._balancer = None
        self._loop = None
        self._seq_mark = 0
        self._last_trigger_mono: Optional[float] = None
        self._fence_marks: Optional[deque] = None
        self._queue: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        self._prior_eventlog_enabled: Optional[bool] = None
        self._counter = 0
        #: id -> summary row, newest-last (mirrors the retention ring)
        self._index: "Dict[str, dict]" = {}
        self.captured = 0
        self.coalesced = 0
        self.dropped = 0
        self.plane_errors = 0

    # -- ownership ---------------------------------------------------------
    def install(self, balancer=None, owner: Optional[object] = None) -> bool:
        """Arm the recorder for `balancer` (its alert engine, journal and
        books are the per-process evidence sources). Refused no-op when
        disabled or already owned — the host-observatory contract: first
        balancer in a shared test process wins, the rest piggyback."""
        with self._lock:
            if self._owner is not None:
                return False
            if self._from_env:
                self.config = incidents_config()
                self.enabled = bool(self.config.enabled)
        if not self.enabled:
            return False
        with self._lock:
            if self._owner is not None:
                return False
            self._owner = owner if owner is not None else object()
            self._balancer = balancer
            self._fence_marks = deque(
                maxlen=max(1, int(self.config.fence_burst_n)))
            self._queue = queue.Queue(maxsize=4)
            self._worker = threading.Thread(
                target=self._worker_loop, name="incident-recorder",
                daemon=True)
            self._worker.start()
        if balancer is not None:
            seq = getattr(balancer, "_journal_seq", 0)
            self._seq_mark = int(seq or 0)
            engine = getattr(getattr(balancer, "anomaly", None),
                             "engine", None)
            if engine is not None and self._on_alert not in engine.listeners:
                engine.listeners.append(self._on_alert)
        # structural distress arrives through the event log; incidents
        # being armed forces it on (remembering the prior state so
        # uninstall restores a fleet-observatory-off process exactly)
        self._prior_eventlog_enabled = GLOBAL_EVENT_LOG.enabled
        GLOBAL_EVENT_LOG.enabled = True
        GLOBAL_EVENT_LOG.add_listener(self._on_event)
        os.makedirs(self.directory, exist_ok=True)
        # the index mirrors THIS directory's retention ring: a re-arm
        # (possibly pointed elsewhere by a config refresh) must not serve
        # rows for bundles a previous installation wrote somewhere else
        with self._lock:
            self._index.clear()
        self._load_index()
        return True

    def uninstall(self, owner: Optional[object] = None) -> None:
        with self._lock:
            if self._owner is None:
                return
            if owner is not None and owner is not self._owner:
                return
            self._owner = None
            balancer, self._balancer = self._balancer, None
            q, self._queue = self._queue, None
            worker, self._worker = self._worker, None
            prior = self._prior_eventlog_enabled
            self._prior_eventlog_enabled = None
            self._last_trigger_mono = None
        GLOBAL_EVENT_LOG.remove_listener(self._on_event)
        if prior is not None:
            GLOBAL_EVENT_LOG.enabled = prior
        engine = getattr(getattr(balancer, "anomaly", None), "engine", None)
        if engine is not None and self._on_alert in engine.listeners:
            engine.listeners.remove(self._on_alert)
        if q is not None:
            try:
                q.put_nowait(None)  # wake + stop the worker
            except queue.Full:
                pass
        if worker is not None:
            worker.join(timeout=5.0)

    @property
    def directory(self) -> str:
        d = self.config.directory
        if d:
            return d
        import tempfile
        return os.path.join(tempfile.gettempdir(),
                            f"whisk-incidents-{os.getpid()}")

    # -- triggers ----------------------------------------------------------
    def _on_alert(self, now, rule, labels, old, new, value) -> None:
        # owner check before building the trigger payload: the disabled /
        # uninstalled path must allocate nothing (tracemalloc-asserted)
        if new != "firing" or self._owner is None:
            return
        self._trigger(f"alert:{rule.name}", severity=rule.severity,
                      labels=dict(labels),
                      value=None if value is None else float(value))

    def _on_event(self, rec: dict) -> None:
        if self._owner is None:
            return
        kind = rec.get("kind")
        if kind in DISTRESS_KINDS:
            self._trigger(f"event:{kind}", severity="warning",
                          labels={k: v for k, v in rec.items()
                                  if k not in ("kind", "mono", "ts", "seq")})
        elif kind == "fence_discard":
            now = time.monotonic()
            with self._lock:
                marks = self._fence_marks
                if marks is None:
                    return
                marks.append(now)
                burst = (len(marks) == marks.maxlen
                         and now - marks[0]
                         <= self.config.fence_burst_window_s)
                if burst:
                    marks.clear()
            if burst:
                self._trigger("event:fence_discard_burst",
                              severity="warning",
                              labels={"n": self.config.fence_burst_n,
                                      "window_s":
                                      self.config.fence_burst_window_s})

    def _trigger(self, reason: str, severity: str = "warning",
                 labels: Optional[dict] = None,
                 value: Optional[float] = None) -> None:
        now = time.monotonic()
        with self._lock:
            if self._owner is None:
                return
            if (self._last_trigger_mono is not None
                    and now - self._last_trigger_mono
                    < self.config.debounce_s):
                self.coalesced += 1
                return
            self._last_trigger_mono = now
            self._counter += 1
            q = self._queue
            coalesced_before = self.coalesced
        # the loop handle for the two loop-only reads; alert triggers fire
        # on the evaluation tick so this almost always succeeds
        try:
            import asyncio
            self._loop = asyncio.get_running_loop()
        except RuntimeError:
            pass
        job = {"reason": reason, "severity": severity,
               "labels": labels or {}, "value": value,
               "ts": time.time(), "mono": now,
               "counter": self._counter,
               "coalesced_mark": coalesced_before}
        if q is None:
            return
        try:
            q.put_nowait(job)
        except queue.Full:
            with self._lock:
                self.dropped += 1

    # -- capture (worker thread) -------------------------------------------
    def _worker_loop(self) -> None:
        while True:
            q = self._queue
            if q is None:
                return
            try:
                job = q.get(timeout=1.0)
            except queue.Empty:
                continue
            if job is None:
                return
            try:
                self._capture(job)
            except Exception as e:  # noqa: BLE001 — the recorder degrades,
                # it never takes the process down with the incident
                if self.logger is not None:
                    self.logger.warn(None, f"incident capture failed: "
                                           f"{e!r}", "IncidentRecorder")

    def _on_loop(self, fn: Callable[[], Any], timeout: float = 5.0):
        """Run `fn` on the event loop thread and wait for the result —
        for the reads that must be journal-seq-consistent with the loop's
        state mutations."""
        loop = self._loop
        if loop is None or loop.is_closed():
            raise RuntimeError("no event loop handle")
        import concurrent.futures
        fut: "concurrent.futures.Future" = concurrent.futures.Future()

        def run():
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
        loop.call_soon_threadsafe(run)
        return fut.result(timeout)

    def _capture(self, job: dict) -> None:
        cfg = self.config
        bal = self._balancer
        planes: Dict[str, Any] = {}
        errors: Dict[str, str] = {}

        def grab(name: str, fn: Callable[[], Any]) -> None:
            try:
                planes[name] = fn()
            except Exception as e:  # noqa: BLE001 — per-plane guard
                errors[name] = repr(e)
                with self._lock:
                    self.plane_errors += 1

        anomaly = getattr(bal, "anomaly", None)
        if anomaly is not None:
            grab("alerts", lambda: anomaly.alerts_report(limit=50))
            # device pull ok: we are on the worker thread, not the loop
            grab("anomaly_scores", lambda: anomaly.anomalies_report())
        telemetry = getattr(bal, "telemetry", None)
        if telemetry is not None:
            names = getattr(bal, "_telemetry_invoker_names", None)
            grab("telemetry_slo",
                 lambda: telemetry.slo_report(names() if callable(names)
                                              else None))
        waterfall = getattr(bal, "waterfall", None)
        if waterfall is not None:
            grab("waterfall", lambda: waterfall.report(recent=8))
        fr = getattr(bal, "flight_recorder", None)
        if fr is not None:
            grab("flight_recorder",
                 lambda: fr.recent(cfg.recent_batches, with_decisions=True))
        from .hostprof import GLOBAL_HOST_OBSERVATORY as obs
        grab("host", obs.snapshot)
        if cfg.profiler_capture_s > 0 and self._loop is not None:
            def _prof():
                import asyncio
                return asyncio.run_coroutine_threadsafe(
                    obs.capture(cfg.profiler_capture_s),
                    self._loop).result(cfg.profiler_capture_s + 5.0)
            grab("host_profile", _prof)
        grab("traces", lambda: self._traces_in_window(job))
        grab("events", lambda: self._events_in_window(job))
        if bal is not None and hasattr(bal, "snapshot_parts"):
            def _books():
                parts = self._on_loop(bal.snapshot_parts)
                # heavy device->host conversion stays on THIS thread
                return bal.snapshot(parts)
            grab("books", _books)
        # books FIRST, then the journal window bounded at the books'
        # journal_seq: the time-travel debugger replays the window and
        # diffs against the captured books, so the two must describe the
        # same instant even while traffic keeps flowing
        books = planes.get("books")
        to_seq = (books or {}).get("journal_seq")
        grab("journal", lambda: self._journal_window(bal, to_seq=to_seq))

        aids = self._collect_aids(planes)
        with self._lock:
            coalesced = self.coalesced - job["coalesced_mark"]
        payload = {
            "version": BUNDLE_VERSION,
            "id": f"inc-{int(job['ts'] * 1000):013x}-{job['counter']:04d}",
            "ts": job["ts"],
            "reason": job["reason"],
            "severity": job["severity"],
            "labels": job["labels"],
            "value": job["value"],
            "coalesced": coalesced,
            "window_s": cfg.window_s,
            "identity": identity(),
            "planes": planes,
            "plane_errors": errors,
            "activation_ids": sorted(aids),
        }
        path = os.path.join(self.directory, f"{payload['id']}.wbb")
        write_bundle(path, payload)
        with self._lock:
            self.captured += 1
            self._index[payload["id"]] = _summary(payload)
        self._prune()
        if self.logger is not None:
            self.logger.warn(
                None, f"incident {payload['id']} captured "
                f"({job['reason']}, {len(planes)} planes, "
                f"coalesced={coalesced}) -> {path}", "IncidentRecorder")

    def _traces_in_window(self, job: dict) -> List[dict]:
        from .tracestore import GLOBAL_TRACE_STORE
        cutoff = job["ts"] - self.config.window_s
        out = [e for e in GLOBAL_TRACE_STORE.entries()
               if float(e.get("ts", 0.0)) >= cutoff]
        return out[-self.config.recent_traces:]

    def _events_in_window(self, job: dict) -> List[dict]:
        cutoff = job["mono"] - self.config.window_s
        out = [r for r in GLOBAL_EVENT_LOG.recent(self.config.recent_events)
               if float(r.get("mono", 0.0)) >= cutoff]
        return out

    def _journal_window(self, bal, to_seq: Optional[int] = None) -> dict:
        """The journal seq range covering the window, records embedded so
        owdebug replays the bundle standalone. `from_seq` is the mark laid
        at install / the previous capture — the honest 'everything since
        we last looked' window; `to_seq` is the captured books' seq when
        books were captured (replay-parity anchor), the live seq
        otherwise."""
        journal = getattr(bal, "journal", None)
        from_seq = self._seq_mark
        if to_seq is None:
            to_seq = int(getattr(bal, "_journal_seq", 0) or 0)
        to_seq = int(to_seq)
        out: Dict[str, Any] = {"from_seq": from_seq, "to_seq": to_seq,
                               "directory": None, "records": []}
        if journal is None:
            return out
        out["directory"] = getattr(journal, "dir", None)
        try:
            journal.flush(timeout=2.0)
        except Exception:  # noqa: BLE001 — a stalled journal is itself
            pass           # the incident; capture what is durable
        recs = [r for r in journal.records(after_seq=from_seq)
                if int(r.get("seq", 0)) <= to_seq or to_seq == 0]
        if len(recs) > self.config.max_journal_records:
            out["truncated"] = len(recs) - self.config.max_journal_records
            recs = recs[-self.config.max_journal_records:]
        out["records"] = recs
        self._seq_mark = max(from_seq, to_seq)
        return out

    @staticmethod
    def _collect_aids(planes: dict) -> set:
        """Activation ids referenced by the bundle — the flight recorder's
        decision rows plus the journal batch records' `aids` — so one
        activation id walks recorder -> trace -> bundle (explain
        cross-links)."""
        aids = set()
        for rec in planes.get("flight_recorder") or ():
            for d in rec.get("decisions") or ():
                a = d.get("activation_id")
                if a:
                    aids.add(str(a))
        j = planes.get("journal") or {}
        for rec in j.get("records") or ():
            for a in rec.get("aids") or ():
                if a:
                    aids.add(str(a))
        for e in planes.get("traces") or ():
            a = e.get("activation_id")
            if a:
                aids.add(str(a))
        return aids

    # -- retention + read side ---------------------------------------------
    def _bundle_files(self) -> List[str]:
        try:
            names = [n for n in os.listdir(self.directory)
                     if n.startswith("inc-") and n.endswith(".wbb")]
        except OSError:
            return []
        return sorted(names)  # ids embed a ms timestamp: sorted == oldest

    def _prune(self) -> None:
        keep = max(1, int(self.config.retention))
        files = self._bundle_files()
        for name in files[:-keep] if len(files) > keep else []:
            try:
                os.unlink(os.path.join(self.directory, name))
            except OSError:
                pass
            with self._lock:
                self._index.pop(name[:-len(".wbb")], None)

    def _load_index(self) -> None:
        """Adopt bundles already on disk (a restarted controller keeps its
        forensic history)."""
        for name in self._bundle_files()[-int(self.config.retention):]:
            iid = name[:-len(".wbb")]
            with self._lock:
                if iid in self._index:
                    continue
            payload = read_bundle(os.path.join(self.directory, name))
            if payload is not None:
                with self._lock:
                    self._index[payload["id"]] = _summary(payload)

    def list_incidents(self) -> List[dict]:
        """Newest-first summary rows (the `/admin/incidents` body)."""
        with self._lock:
            rows = list(self._index.values())
        rows.sort(key=lambda r: r.get("ts") or 0.0, reverse=True)
        return rows

    def get(self, incident_id: str) -> Optional[dict]:
        """Full bundle payload by id; None when unknown/corrupt."""
        if ("/" in incident_id or "\\" in incident_id
                or not incident_id.startswith("inc-")):
            return None
        return read_bundle(os.path.join(self.directory,
                                        f"{incident_id}.wbb"))

    def incidents_for_activation(self, activation_id: str) -> List[str]:
        """Incident ids whose bundles reference `activation_id` — the
        explain cross-link. Summary rows only carry the COUNT, so this
        reads the (retention-bounded) bundles; explain is a cold path."""
        out = []
        for row in self.list_incidents():
            if not row.get("activation_ids"):
                continue
            payload = self.get(row["id"])
            if payload and activation_id in (payload.get("activation_ids")
                                             or ()):
                out.append(row["id"])
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "installed": self._owner is not None,
                    "directory": self.directory,
                    "captured": self.captured,
                    "coalesced": self.coalesced,
                    "dropped": self.dropped,
                    "plane_errors": self.plane_errors,
                    "bundles": len(self._index),
                    "seq_mark": self._seq_mark}

    # -- exposition --------------------------------------------------------
    def prometheus_text(self, openmetrics: bool = False) -> str:
        if not self.enabled:
            return ""
        with self._lock:
            counters = [
                ("openwhisk_incidents_captured_total", self.captured),
                ("openwhisk_incidents_coalesced_total", self.coalesced),
                ("openwhisk_incidents_dropped_total", self.dropped),
                ("openwhisk_incidents_plane_errors_total",
                 self.plane_errors),
            ]
            bundles = len(self._index)
        out: List[str] = []
        for name, value in counters:
            # unlabeled counter, tracestore idiom: OM types the base name,
            # samples keep the _total suffix in both formats
            base = name[:-len("_total")] if openmetrics else name
            out += [f"# TYPE {base} counter", f"{name} {int(value)}"]
        out += ["# TYPE openwhisk_incidents_bundles gauge",
                f"openwhisk_incidents_bundles {bundles}"]
        return "\n".join(out)


#: the process-global recorder (GLOBAL_HOST_OBSERVATORY pattern: triggers
#: span layers — invoker fence discards, journal flush stalls — so the
#: instance must too). Rebuilt-from-env on import; tests construct their
#: own `IncidentRecorder(IncidentConfig(...))` instead of mutating this.
GLOBAL_INCIDENTS = IncidentRecorder()

"""ISSUE 12: columnar hot path — batch wire records, batch-shaped
completion pipeline, sharded front end.

Covers the acceptance contracts:
  * wire parity: a batch record decodes to field-identical messages
    (fuzzed over optional columns), and every batch payload sniffs as
    one while plain payloads never do;
  * encode-exactly-once: a message riding a batch frame is serialized
    once, at flush, with the serde byte counters seeing exactly the
    batch payload's bytes;
  * off-switches: batchWire=false ships byte-identical serial payloads;
    batchedAck=false replays a decoded frame through the serial per-ack
    path with identical state transitions;
  * out-of-order / partial batch acks: a completion frame spanning two
    dispatch batches, and a frame holding an ack for an evicted entry,
    must not desync the waterfall stamps or the inflight gauge;
  * sharded front end: shards=1 builds nothing (bit-exact default);
    shards>=2 decides per-namespace sequences exactly like the serial
    path (parity fuzz) and propagates the serial exceptions.
"""
from __future__ import annotations

import asyncio
import random
import time

import pytest

from openwhisk_tpu.controller.entitlement import (ACTIVATE,
                                                  LocalEntitlementProvider,
                                                  ThrottleRejectRequest)
from openwhisk_tpu.controller.frontend import (FrontendConfig,
                                               FrontendShardPlane,
                                               maybe_shard_frontend)
from openwhisk_tpu.core.entity import (ActivationId, ActivationResponse,
                                       ControllerInstanceId, EntityPath,
                                       Identity, InvokerInstanceId, MB,
                                       WhiskActivation)
from openwhisk_tpu.core.entity.names import FullyQualifiedEntityName
from openwhisk_tpu.messaging import MemoryMessagingProvider
from openwhisk_tpu.messaging.coalesce import CoalescingProducer
from openwhisk_tpu.messaging.columnar import (ActivationBatchMessage,
                                              AckBatchMessage,
                                              KIND_ACK, KIND_ACTIVATION,
                                              batchable_family,
                                              is_batch_payload, make_batch,
                                              parse_batch)
from openwhisk_tpu.messaging.message import (ActivationMessage,
                                             CombinedCompletionAndResultMessage,
                                             CompletionMessage, PingMessage,
                                             ResultMessage)
from openwhisk_tpu.utils.transaction import TransactionId
from openwhisk_tpu.utils.waterfall import (ActivationWaterfall,
                                           STAGE_COMPLETION_ACK,
                                           STAGE_PUBLISH_ENQUEUE,
                                           WaterfallConfig)


def _ident(ns="guest"):
    return Identity.generate(ns)


def _act_msg(ident, name="act0", i=0, **kw):
    return ActivationMessage(
        TransactionId(), FullyQualifiedEntityName.parse(f"guest/{name}"),
        "1-b", ident, ActivationId.generate(), ControllerInstanceId("0"),
        bool(i % 2), {"x": i}, **kw)


def _activation(ident, msg):
    now = time.time()
    return WhiskActivation(
        EntityPath("guest"), msg.action.name, ident.subject,
        msg.activation_id, now, now,
        ActivationResponse.success({"ok": True}), duration=1)


def _msg_fields(m: ActivationMessage) -> dict:
    j = m.to_json()
    return j


class TestBatchWireRecords:
    def test_activation_batch_roundtrip_fuzz(self):
        rng = random.Random(7)
        idents = [_ident(f"ns{k}") for k in range(3)]
        for trial in range(20):
            msgs = []
            for i in range(rng.randint(1, 12)):
                kw = {}
                if rng.random() < 0.3:
                    kw["cause"] = ActivationId.generate()
                if rng.random() < 0.3:
                    kw["trace_context"] = {"traceparent": f"00-{i}"}
                if rng.random() < 0.3:
                    kw["init_args"] = {"k": i}
                if rng.random() < 0.5:
                    kw["fence_epoch"] = rng.choice([3, 3, 7])
                msgs.append(_act_msg(idents[rng.randrange(3)],
                                     name=f"a{i % 4}", i=i, **kw))
            raw = ActivationBatchMessage(msgs).serialize()
            assert is_batch_payload(raw)
            kind, out = parse_batch(raw)
            assert kind == KIND_ACTIVATION
            assert len(out) == len(msgs)
            for a, b in zip(msgs, out):
                assert _msg_fields(a) == _msg_fields(b)

    def test_ack_batch_roundtrip_all_kinds(self):
        ident = _ident()
        inv = InvokerInstanceId(0, user_memory=MB(512))
        msgs = [_act_msg(ident, i=i) for i in range(3)]
        acks = [
            CompletionMessage(msgs[0].transid, msgs[0].activation_id, True,
                              inv),
            ResultMessage(msgs[1].transid, _activation(ident, msgs[1])),
            CombinedCompletionAndResultMessage(
                msgs[2].transid, _activation(ident, msgs[2]), inv),
        ]
        raw = AckBatchMessage(acks).serialize()
        assert is_batch_payload(raw)
        kind, out = parse_batch(raw)
        assert kind == KIND_ACK
        for a, b in zip(acks, out):
            assert a.kind == b.kind
            assert a.activation_id == b.activation_id
            assert a.is_system_error == b.is_system_error
            assert (a.invoker is None) == (b.invoker is None)
            if a.invoker is not None:
                assert a.invoker.as_string == b.invoker.as_string
            assert (a.activation is None) == (b.activation is None)
            if a.activation is not None:
                # `updated` is stamped at to_json() call time — exclude
                ja = a.activation.to_json()
                jb = b.activation.to_json()
                ja.pop("updated"), jb.pop("updated")
                assert ja == jb

    def test_plain_payloads_never_sniff_as_batch(self):
        ident = _ident()
        msg = _act_msg(ident)
        assert not is_batch_payload(msg.serialize())
        ack = CombinedCompletionAndResultMessage(
            msg.transid, _activation(ident, msg),
            InvokerInstanceId(0, user_memory=MB(512)))
        assert not is_batch_payload(ack.serialize())
        assert not is_batch_payload(PingMessage(
            InvokerInstanceId(0, user_memory=MB(512))).serialize())

    def test_batchable_family(self):
        ident = _ident()
        msg = _act_msg(ident)
        assert batchable_family(msg) == KIND_ACTIVATION
        assert batchable_family(
            ResultMessage(msg.transid, _activation(ident, msg))) == KIND_ACK
        assert batchable_family(PingMessage(
            InvokerInstanceId(0, user_memory=MB(512)))) is None

    def test_dedup_tables_shrink_the_frame(self):
        """The columnar record's dedup must beat N serial encodes on a
        same-user batch — that IS the serde win being shipped."""
        ident = _ident()
        msgs = [_act_msg(ident, name=f"a{i % 2}", i=i) for i in range(16)]
        batch_bytes = len(ActivationBatchMessage(msgs).serialize())
        serial_bytes = sum(len(m.serialize()) for m in msgs)
        assert batch_bytes < serial_bytes / 2


class _SpyProducer:
    """Records send_many items; no transport."""

    def __init__(self):
        self.shipped = []

    async def send_many(self, items):
        self.shipped.append(list(items))

    async def send(self, topic, msg):
        await self.send_many([(topic, msg if isinstance(msg, bytes)
                               else msg.serialize(), msg)])

    async def close(self):
        pass

    @property
    def sent_count(self):
        return sum(len(b) for b in self.shipped)


class TestCoalescerBatchWire:
    def _drive(self, batch_wire: bool, msgs, topic="invoker0"):
        async def go():
            spy = _SpyProducer()
            prod = CoalescingProducer(spy, max_batch=64,
                                      batch_wire=batch_wire)
            await asyncio.gather(*[prod.send(topic, m) for m in msgs])
            await prod.flush()
            return spy.shipped

        return asyncio.run(go())

    def test_batch_wire_one_payload_per_topic(self):
        ident = _ident()
        msgs = [_act_msg(ident, i=i) for i in range(8)]
        shipped = self._drive(True, msgs)
        items = [it for batch in shipped for it in batch]
        assert len(items) == 1
        topic, payload, batch_msg = items[0]
        assert is_batch_payload(payload)
        _kind, out = parse_batch(payload)
        assert [m.activation_id.asString for m in out] == \
            [m.activation_id.asString for m in msgs]
        # the batch message exposes the ids for the produce stamp
        assert batch_msg.activation_ids == \
            [m.activation_id.asString for m in msgs]

    def test_off_switch_serial_payloads_byte_exact(self):
        ident = _ident()
        msgs = [_act_msg(ident, i=i) for i in range(4)]
        shipped = self._drive(False, msgs)
        items = [it for batch in shipped for it in batch]
        assert len(items) == 4
        for (topic, payload, m), orig in zip(items, msgs):
            assert payload == orig.serialize()

    def test_lone_message_stays_plain_format(self):
        ident = _ident()
        shipped = self._drive(True, [_act_msg(ident)])
        items = [it for batch in shipped for it in batch]
        assert len(items) == 1
        assert not is_batch_payload(items[0][1])

    def test_unbatchable_messages_pass_through(self):
        inv = InvokerInstanceId(0, user_memory=MB(512))
        shipped = self._drive(True, [PingMessage(inv), PingMessage(inv)],
                              topic="health")
        items = [it for batch in shipped for it in batch]
        assert len(items) == 2
        for _t, payload, m in items:
            assert not is_batch_payload(payload)

    def test_encode_exactly_once_byte_counted(self):
        """The satellite contract: with the batch wire on, a batched
        message is encoded exactly once — the serde serialize counter
        books exactly the batch payload's bytes, not N message encodes
        plus a re-frame."""
        from openwhisk_tpu.utils.hostprof import GLOBAL_HOST_OBSERVATORY

        ident = _ident()
        msgs = [_act_msg(ident, i=i) for i in range(6)]
        obs = GLOBAL_HOST_OBSERVATORY
        was_enabled = obs.enabled
        obs.enabled = True
        obs.reset()
        try:
            shipped = self._drive(True, msgs)
            items = [it for b in shipped for it in b]
            payload = items[0][1]
            snap = obs.snapshot()
            row = {(r["hop"], r["direction"]): r
                   for r in snap.get("serde", [])}
            ser = row.get(("activation", "serialize"))
            assert ser is not None
            assert ser["count"] == 1
            assert ser["bytes"] == len(payload)
        finally:
            obs.enabled = was_enabled
            obs.reset()

    def test_send_batch_resolves_per_item(self):
        """send_batch awaits one gather over futures; a flush failure
        still propagates to the caller."""
        ident = _ident()

        class _Boom(_SpyProducer):
            async def send_many(self, items):
                raise RuntimeError("bus down")

        async def go():
            prod = CoalescingProducer(_Boom(), max_batch=8,
                                      batch_wire=True)
            with pytest.raises(RuntimeError):
                await prod.send_batch("t", [_act_msg(ident, i=i)
                                            for i in range(3)])

        asyncio.run(go())


def _mk_balancer(monkeypatch=None, batched_ack=True):
    """A CommonLoadBalancer with stub planes, enough for ack processing."""
    from openwhisk_tpu.controller.loadbalancer.base import CommonLoadBalancer
    from openwhisk_tpu.utils.waterfall import ActivationWaterfall

    provider = MemoryMessagingProvider()
    bal = CommonLoadBalancer(provider, ControllerInstanceId("0"),
                             waterfall=ActivationWaterfall(
                                 WaterfallConfig(enabled=True)))
    bal.batched_ack = batched_ack
    return bal


class TestBatchedAckPipeline:
    def _setup_entries(self, bal, n, action=None):
        import bench
        ident = _ident()
        action = action or bench._bench_action("b0", memory=128)
        inv = InvokerInstanceId(0, user_memory=MB(512))
        msgs = []
        for i in range(n):
            m = _act_msg(ident, name="b0", i=i)
            bal.waterfall.begin(m.activation_id.asString)
            bal.waterfall.stamp(m.activation_id.asString,
                                STAGE_PUBLISH_ENQUEUE)
            bal.setup_activation(m, action, inv)
            msgs.append(m)
        return msgs, inv, ident

    def test_batch_ack_frame_completes_all(self):
        async def go():
            bal = _mk_balancer()
            msgs, inv, ident = self._setup_entries(bal, 5)
            acks = [CombinedCompletionAndResultMessage(
                m.transid, _activation(ident, m), inv) for m in msgs]
            raw = AckBatchMessage(acks).serialize()
            bal.process_acknowledgement_frame(raw)
            assert bal.total_active_activations == 0
            assert not bal.activation_slots
            # every stage vector folded exactly once
            assert bal.waterfall._finished == 5
            assert bal.waterfall.active == 0
            assert bal.metrics.counter_value(
                "loadbalancer_completion_ack_regular") == 5
            await bal.close()

        asyncio.run(go())

    def test_batched_ack_off_replays_serially_bit_exact(self):
        """batchedAck=false: the frame decodes once but each ack walks
        process_completion — final books identical to the batched path."""
        async def go():
            out = {}
            for flag in (True, False):
                bal = _mk_balancer(batched_ack=flag)
                msgs, inv, ident = self._setup_entries(bal, 4)
                acks = [CombinedCompletionAndResultMessage(
                    m.transid, _activation(ident, m), inv) for m in msgs]
                bal.process_acknowledgement_frame(
                    AckBatchMessage(acks).serialize())
                out[flag] = (bal.total_active_activations,
                             len(bal.activation_slots),
                             bal.waterfall._finished,
                             bal.metrics.counter_value(
                                 "loadbalancer_completion_ack_regular"))
                await bal.close()
            assert out[True] == out[False] == (0, 0, 4, 4)

        asyncio.run(go())

    def test_cross_dispatch_batch_acks_no_desync(self):
        """Out-of-order satellite: ONE completion frame acking
        activations from TWO different dispatch batches (interleaved,
        reversed order) — inflight gauge and waterfall must both land at
        zero with every vector folded."""
        async def go():
            bal = _mk_balancer()
            msgs_a, inv, ident = self._setup_entries(bal, 3)
            msgs_b, _, _ = self._setup_entries(bal, 3)
            assert bal.total_active_activations == 6
            mixed = [msgs_b[2], msgs_a[0], msgs_b[0], msgs_a[2],
                     msgs_b[1], msgs_a[1]]
            acks = [CombinedCompletionAndResultMessage(
                m.transid, _activation(ident, m), inv) for m in mixed]
            bal.process_acknowledgement_frame(
                AckBatchMessage(acks).serialize())
            assert bal.total_active_activations == 0
            assert bal.waterfall._finished == 6
            assert bal.waterfall.active == 0
            await bal.close()

        asyncio.run(go())

    def test_partial_batch_with_evicted_entry(self):
        """Partial satellite: one ack in the frame targets an entry that
        was already completed (evicted) — it must count as
        regularAfterForced without touching the live entries' books, and
        the rest of the frame completes normally."""
        async def go():
            bal = _mk_balancer()
            msgs, inv, ident = self._setup_entries(bal, 3)
            # evict msgs[1] through the serial path first (a forced
            # timeout), so its later batch ack is a late duplicate
            bal.process_completion(msgs[1].activation_id, forced=True,
                                   is_system_error=False, invoker=inv)
            assert bal.total_active_activations == 2
            acks = [CombinedCompletionAndResultMessage(
                m.transid, _activation(ident, m), inv) for m in msgs]
            bal.process_acknowledgement_frame(
                AckBatchMessage(acks).serialize())
            assert bal.total_active_activations == 0
            assert not bal.activation_slots
            assert bal.metrics.counter_value(
                "loadbalancer_completion_ack_regular") == 2
            assert bal.metrics.counter_value(
                "loadbalancer_completion_ack_regularAfterForced") == 1
            # the forced fold + the two batch folds: nothing leaked
            assert bal.waterfall.active == 0
            await bal.close()

        asyncio.run(go())

    def test_finish_many_equals_serial_finish(self):
        wf = ActivationWaterfall(WaterfallConfig(enabled=True))
        wf2 = ActivationWaterfall(WaterfallConfig(enabled=True))
        aids = [f"{i:032x}" for i in range(6)]
        t0 = time.monotonic_ns()
        for w in (wf, wf2):
            for i, aid in enumerate(aids):
                w.begin(aid, t0_ns=t0)
                w.stamp(aid, STAGE_PUBLISH_ENQUEUE, t0 + 1000 * (i + 1))
                w.stamp(aid, STAGE_COMPLETION_ACK, t0 + 2000 * (i + 1))
        for aid in aids:
            wf.finish(aid)
        assert wf2.finish_many(aids) == 6
        assert wf._hist == wf2._hist
        assert wf._sum_us == wf2._sum_us
        assert wf._finished == wf2._finished
        assert wf._total_hist == wf2._total_hist


class TestInvokerBatchPickup:
    def test_feed_consume_extra_backpressure(self):
        from openwhisk_tpu.messaging.connector import MessageFeed

        class _C:
            async def peek(self, n, timeout=0.5):
                return []

            def commit(self):
                pass

            async def close(self):
                pass

        feed = MessageFeed("t", _C(), 4, lambda p: None)
        assert feed.free_capacity == 4
        feed.consume_extra(6)
        assert feed.free_capacity == -2
        for _ in range(7):
            feed.processed()
        assert feed.free_capacity == 5

    def test_echo_fleet_roundtrip_over_batch_wire(self):
        """End-to-end over the memory bus: a coalesced dispatch ships ONE
        batch frame, the echo invoker decodes it once and acks in one
        ack frame, the balancer's batch ack path completes every
        promise. This covers bench's echo + the balancer feed wiring."""
        import bench

        async def go():
            from openwhisk_tpu.controller.loadbalancer import TpuBalancer
            from openwhisk_tpu.controller.loadbalancer.base import HEALTHY
            provider = MemoryMessagingProvider()
            bal = TpuBalancer(provider, ControllerInstanceId("0"),
                              managed_fraction=1.0, blackbox_fraction=0.0)
            await bal.start()
            feeds, stop = await bench._echo_fleet(provider, 2)
            for _ in range(80):
                health = await bal.invoker_health()
                if sum(h.status == HEALTHY for h in health) >= 2:
                    break
                await asyncio.sleep(0.25)
            ident = _ident()
            action = bench._bench_action("wire0", memory=128)
            msgs = [_act_msg(ident, name="wire0", i=i) for i in range(16)]
            promises = await asyncio.gather(*[
                bal.publish(action, m) for m in msgs])
            results = await asyncio.gather(*[
                asyncio.wait_for(p, 10) for p in promises])
            from openwhisk_tpu.messaging.coalesce import _STATS
            wire_batches = _STATS["wire_batches"]
            await stop()
            await bal.close()
            for f in feeds:
                await f.stop()
            return results, wire_batches

        results, wire_batches = asyncio.run(go())
        assert len(results) == 16
        assert all(r.response.is_success for r in results)
        assert wire_batches > 0  # the batch wire actually carried frames


class TestFrontendSharding:
    def test_default_builds_nothing(self):
        p = LocalEntitlementProvider(None)
        assert p.frontend is None
        assert maybe_shard_frontend(p, FrontendConfig(shards=1)) is None

    def test_shard_of_deterministic_and_balanced(self):
        p = LocalEntitlementProvider(
            None, frontend_config=FrontendConfig(shards=4))
        try:
            plane = p.frontend
            assert isinstance(plane, FrontendShardPlane)
            shards = {plane.shard_of(f"ns-{i}") for i in range(64)}
            assert shards == {0, 1, 2, 3}
            assert plane.shard_of("ns-7") == plane.shard_of("ns-7")
        finally:
            plane.close()

    def test_parity_fuzz_vs_serial(self):
        """Per-namespace decision sequences through 3 shards equal the
        single-loop serial path's, including rejection texts."""
        async def drive(provider, idents, seq):
            out = []
            for i in seq:
                try:
                    await provider.check(
                        idents[i], ACTIVATE,
                        str(idents[i].namespace.name), throttle=True)
                    out.append((i, True, None))
                except ThrottleRejectRequest as e:
                    out.append((i, False, e.message))
            return out

        async def go():
            rng = random.Random(13)
            idents = [_ident(f"ns{k}") for k in range(10)]
            seq = [rng.randrange(10) for _ in range(300)]
            serial = LocalEntitlementProvider(None,
                                              invocations_per_minute=15)
            sharded = LocalEntitlementProvider(
                None, invocations_per_minute=15,
                frontend_config=FrontendConfig(shards=3))
            try:
                a = await drive(serial, idents, seq)
                b = await drive(sharded, idents, seq)
            finally:
                await sharded.close()
            from collections import defaultdict
            pa, pb = defaultdict(list), defaultdict(list)
            for i, ok, text in a:
                pa[i].append((ok, text))
            for i, ok, text in b:
                pb[i].append((ok, text))
            assert pa == pb
            assert sharded.frontend.routed == len(seq)

        asyncio.run(go())

    def test_concurrency_throttle_routes_through_shards(self):
        """The concurrency limit (backed by the balancer's counters)
        rejects through the shard plane with the serial message."""
        class _LB:
            def active_activations_for(self, ns):
                return 99

        async def go():
            p = LocalEntitlementProvider(
                _LB(), concurrent_invocations=10,
                frontend_config=FrontendConfig(shards=2))
            try:
                with pytest.raises(ThrottleRejectRequest) as ei:
                    await p.check(_ident(), ACTIVATE, "guest",
                                  throttle=True)
                assert "concurrent" in str(ei.value)
            finally:
                await p.close()

        asyncio.run(go())

from .container import (Container, ContainerError, InitializationError,
                        RunResult, ACTIVATION_LOG_SENTINEL)
from .factory import ContainerFactory, ContainerPoolConfig
from .process_factory import (ProcessContainer, ProcessContainerFactory,
                              ProcessContainerFactoryProvider)
from .docker_factory import DockerContainerFactory, docker_available
from .pool import ContainerPool, Run
from .proxy import ContainerProxy, ContainerData
from .logstore import ContainerLogStore, ContainerLogStoreProvider

__all__ = [n for n in dir() if not n.startswith("_")]

"""The four headline simulations of the reference performance harness.

Parity with tests/performance (tests/performance/README.md):
  latency     warm end-to-end blocking-invoke latency, concurrency 1
              (wrk latency test :31-43 + Gatling LatencySimulation :88-121)
  throughput  sustained blocking throughput on one warm action, concurrency C
              (wrk throughput :45-52 + BlockingInvokeOneActionSimulation
              :124-140)
  cold        cold-start blocking throughput — every invoke hits a fresh
              action so no warm container can be reused
              (ColdBlockingInvokeSimulation)
  apiv1       CRUD/API throughput over /api/v1 — put/get/list/delete cycle
              (ApiV1Simulation :63-86)

Thresholds come from the environment exactly as in the reference
(MEAN_RESPONSE_TIME, MAX_MEAN_RESPONSE_TIME, REQUESTS_PER_SEC,
MIN_REQUESTS_PER_SEC); without them the run is report-only.

    python tests/performance/simulations.py latency --requests 100
    python tests/performance/simulations.py all --requests 50 --concurrency 4
"""
from __future__ import annotations

import argparse
import os
import sys

try:
    from harness import Client, Stats, run_with_standalone, timed_loop
except ImportError:  # imported as a package module (smoke tests)
    from .harness import Client, Stats, run_with_standalone, timed_loop


async def latency_simulation(client: Client, requests: int, **_) -> Stats:
    """Warm latency at concurrency 1: one priming invoke, then the loop."""
    assert await client.put_action("perf-latency") == 200
    await client.invoke("perf-latency")

    async def one(i: int) -> bool:
        status, body = await client.invoke("perf-latency")
        return status == 200 and body["response"]["success"]

    stats = await timed_loop(requests, 1, one)
    stats.name = "latency"
    return stats


async def throughput_simulation(client: Client, requests: int,
                                concurrency: int, **_) -> Stats:
    """Sustained blocking throughput on one warm action."""
    assert await client.put_action("perf-throughput") == 200
    # prime enough warm sandboxes to cover the concurrency
    for _ in range(concurrency):
        await client.invoke("perf-throughput")

    async def one(i: int) -> bool:
        status, _ = await client.invoke("perf-throughput")
        return status == 200

    stats = await timed_loop(requests, concurrency, one)
    stats.name = "throughput"
    return stats


async def cold_simulation(client: Client, requests: int, concurrency: int,
                          **_) -> Stats:
    """Cold-start throughput: a distinct action per invoke (no warm reuse)."""
    for i in range(requests):
        assert await client.put_action(f"perf-cold-{i}") == 200

    async def one(i: int) -> bool:
        status, _ = await client.invoke(f"perf-cold-{i}")
        return status == 200

    stats = await timed_loop(requests, concurrency, one)
    stats.name = "cold"
    return stats


async def apiv1_simulation(client: Client, requests: int, concurrency: int,
                           **_) -> Stats:
    """CRUD cycle throughput: PUT + GET + list + DELETE per iteration."""

    async def one(i: int) -> bool:
        name = f"perf-crud-{i}"
        if await client.put_action(name) != 200:
            return False
        s1, _ = await client.get(f"/namespaces/_/actions/{name}")
        s2, _ = await client.get("/namespaces/_/actions?limit=10")
        s3 = await client.delete(f"/namespaces/_/actions/{name}")
        return (s1, s2, s3) == (200, 200, 200)

    stats = await timed_loop(requests, concurrency, one)
    stats.name = "apiv1"
    return stats


async def soak_simulation(client: Client, requests: int, concurrency: int,
                          duration: float = 60.0, controller=None,
                          **_) -> Stats:
    """Sustained mixed load for `duration` seconds — warm invokes, trigger
    fires and CRUD churn interleaved — then drain and assert the control
    plane leaked nothing: no live activation slots, no concurrency-slot
    refcounts, bounded RSS growth. The reference has no direct equivalent
    (its soak story is the HA/chaos CI); this guards the balancer/invoker
    bookkeeping over time rather than per-request."""
    import asyncio
    import time

    def rss_mb() -> float:
        page = os.sysconf("SC_PAGE_SIZE")
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * page / 1e6

    assert await client.put_action("soak-warm") == 200
    st, _ = await client.put("/namespaces/_/triggers/soak-t", {})
    assert st == 200
    st, _ = await client.put("/namespaces/_/rules/soak-r",
                             {"trigger": "/_/soak-t",
                              "action": "/_/soak-warm"})
    assert st == 200
    await client.invoke("soak-warm")
    rss0 = rss_mb()

    samples: list = []
    errors = 0
    stop = time.monotonic() + duration
    counter = {"i": 0}

    async def one():
        nonlocal errors
        counter["i"] += 1
        i = counter["i"]
        t0 = time.perf_counter()
        try:
            if i % 7 == 5:   # trigger fire path
                st, _ = await client.post("/namespaces/_/triggers/soak-t",
                                          {"n": i})
                ok = st in (200, 202, 204)
            elif i % 7 == 6:  # CRUD churn (unique name: two workers must
                # never race PUT/DELETE on the same entity)
                name = f"soak-crud-{i}"
                ok = await client.put_action(name) == 200
                ok = ok and (await client.delete(
                    f"/namespaces/_/actions/{name}")) == 200
            else:            # warm invoke — 202 is the reference's valid
                # slow-path outcome (ack-wait exhausted -> activation id;
                # the activation still completes and releases its slot)
                st, _ = await client.invoke("soak-warm")
                ok = st in (200, 202)
        except Exception:  # noqa: BLE001 — count, keep soaking
            ok = False
        if ok:
            # successes only, like timed_loop — error latencies must not
            # skew the reported mean/percentiles or inflate rps
            samples.append(time.perf_counter() - t0)
        else:
            errors += 1

    async def worker():
        while time.monotonic() < stop:
            await one()

    await asyncio.gather(*[worker() for _ in range(concurrency)])
    # drain: trigger fires are non-blocking, so rule activations may still
    # be RUNNING when the load stops — poll the books quiescent instead of
    # sleeping a fixed beat (a real leak still fails: nothing releases it)
    if controller is not None:
        bal = controller.load_balancer
        for _ in range(120):
            if bal.total_active_activations == 0:
                break
            await asyncio.sleep(0.25)
    await asyncio.sleep(0.5)  # let the last release fold into the books

    stats = Stats("soak", [x * 1000 for x in samples], duration, errors)
    extra = {"duration_s": round(duration, 1),
             "rss_growth_mb": round(rss_mb() - rss0, 1)}
    if controller is not None:
        bal = controller.load_balancer
        leaks = {
            "active_activations": bal.total_active_activations,
            "activation_slots": len(bal.activation_slots),
        }
        slots = getattr(bal, "_slots", None)
        if slots is not None:
            leaks["conc_refcounts"] = sum(slots.refcount.values())
            leaks["overflow_keys"] = len(slots.overflow)
        extra.update(leaks)
        import json as _json
        print(_json.dumps({"soak_books": extra}))
        assert all(v == 0 for v in leaks.values()), f"leaked: {leaks}"
        assert extra["rss_growth_mb"] < 200, extra
    return stats


SIMULATIONS = {
    "latency": latency_simulation,
    "throughput": throughput_simulation,
    "cold": cold_simulation,
    "apiv1": apiv1_simulation,
}


def run(names, requests: int, concurrency: int, port: int = 13366) -> bool:
    """Run the named simulations against one standalone server; True=pass."""

    async def go(client: Client):
        results = []
        for name in names:
            stats = await SIMULATIONS[name](client, requests=requests,
                                            concurrency=concurrency)
            stats.report()
            results.append(stats.check_thresholds())
        return all(results)

    return run_with_standalone(go, port=port)


def run_soak(duration: float, concurrency: int, port: int = 13366,
             balancer: str = "tpu") -> bool:
    """Soak needs the controller to inspect the balancer's books after the
    drain — run_with_standalone passes it through."""

    async def go(client: Client, controller) -> bool:
        stats = await soak_simulation(
            client, requests=0, concurrency=concurrency,
            duration=duration, controller=controller)
        stats.report()
        return stats.check_thresholds()

    return run_with_standalone(go, port=port, pass_controller=True,
                               balancer=balancer)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("simulation", choices=[*SIMULATIONS, "soak", "all"])
    ap.add_argument("--requests", type=int, default=100)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--port", type=int, default=13366)
    ap.add_argument("--duration", type=float, default=60.0,
                    help="soak: seconds of sustained load")
    ap.add_argument("--balancer", default="tpu",
                    help="soak: lean|tpu (device placement path)")
    args = ap.parse_args()
    if args.simulation == "soak":
        sys.exit(0 if run_soak(args.duration, args.concurrency, args.port,
                               args.balancer) else 1)
    names = list(SIMULATIONS) if args.simulation == "all" else [args.simulation]
    sys.exit(0 if run(names, args.requests, args.concurrency, args.port) else 1)


if __name__ == "__main__":
    main()

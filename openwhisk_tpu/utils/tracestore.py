"""Tail-sampled distributed trace store: keep exactly the traces that matter.

The tracing spine (utils/tracing.py) reports every finished span into a
flat reporter buffer — fine for unit tests, useless for answering "show me
everything that happened to THIS slow activation" once traffic is real:
head-sampling (Dapper's design, see PAPERS.md) must decide at ingress,
before anyone knows whether the request will be interesting. This module
samples at the TAIL instead: spans tee from the tracer's reporter into a
bounded per-trace pending table, and the keep/drop verdict is made at
completion — when the e2e latency, outcome, spill/fence/force flags and
placement-divergence verdict are all known. Kept traces are joined with
the activation's waterfall stage vector (utils/waterfall.py), the flight
recorder's batch digest and the placement-quality digest, serialized once,
and promoted into a kept SeqRingBuffer; everything else ages out without
ever being serialized.

Verdict reasons (the `trace_kept_total{reason}` label, priority order —
the FIRST matching reason is the counter's label):

  error      the activation's outcome was an application/system error
  timeout    the controller force-timed the activation out
  fenced     the activation rode a fenced (HA handoff) dispatch
  spilled    the waterfall row crossed a spill_forward hop
  forced     forced placement row, or an explicit force-trace flag
  divergent  the shadow counterfactual kernel disagreed with placement
  exemplar   an OpenMetrics exemplar was pinned to this trace id (every
             rendered exemplar must resolve via /admin/trace/{id})
  slow       e2e above the live tail threshold (waterfall p99 bucket,
             SLO target fallback)
  floor      the uniform keep floor (deterministic 1-in-N, so the clean
             bulk keep rate equals the configured floor exactly)

Cross-process assembly (`assemble_trace`) merges per-process kept halves
into ONE causal span tree. Clocks are aligned at the bus handoff pairs —
a spilled half's publish_enqueue pins to the origin's spill_forward, an
invoker-side half's invoker_pickup pins to the origin's publish_enqueue —
which deliberately collapses bus transit into the handoff edge (the
conservative alignment; see docs/tpu-balancer.md for the caveats). The
tree telescopes: stage spans are synthesized from the waterfall deltas,
which by construction sum to exactly the measured e2e.

Off-switch: `CONFIG_whisk_tracing_tail_enabled=false` is a TRUE no-op —
the reporter tee is never attached, completions take one attribute check,
no span, dict entry or counter is ever touched (tracemalloc-asserted in
tests/test_tracestore.py).
"""
from __future__ import annotations

import secrets
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from .config import load_config
from .eventlog import identity
from .ring_buffer import SeqRingBuffer
from .tracing import GLOBAL_TRACER, Reporter, Span, Tracer
from .waterfall import (STAGES, STAGE_INVOKER_PICKUP, STAGE_PUBLISH_ENQUEUE,
                        STAGE_SPILL_FORWARD)

#: keep-reason priority: the first match labels `trace_kept_total`
REASONS = ("error", "timeout", "fenced", "spilled", "forced", "divergent",
           "exemplar", "slow", "floor")


@dataclass(frozen=True)
class TraceTailConfig:
    """`CONFIG_whisk_tracing_tail_*` env overrides."""
    enabled: bool = True
    #: kept-trace ring slots (each entry is a fully serialized trace)
    keep_ring: int = 256
    #: in-flight pending traces; past it the oldest ages out (counted)
    pending_limit: int = 4096
    #: uniform keep floor for otherwise-uninteresting traces; 0 disables.
    #: Deterministic 1-in-round(1/floor), not random — the clean-bulk
    #: keep rate is exactly the floor, which the bench rider asserts.
    keep_floor: float = 0.01


def tail_config(data: Optional[dict] = None) -> TraceTailConfig:
    return load_config(TraceTailConfig, data, env_path="tracing.tail")


class _TeeReporter(Reporter):
    """Wraps the tracer's real reporter: every finished span flows to the
    pending table AND the inner sink. `swap_inner` lets
    maybe_enable_zipkin replace the sink without losing the tee."""

    def __init__(self, store: "TraceStore", inner: Reporter):
        self.store = store
        self.inner = inner

    def swap_inner(self, inner: Reporter) -> None:
        self.inner = inner

    def report(self, span: Span) -> None:
        self.store._ingest(span)
        self.inner.report(span)

    # the tracing health gauges read these off whatever reporter is live
    @property
    def sent_spans(self) -> int:
        return getattr(self.inner, "sent_spans", 0)

    @property
    def dropped_spans(self) -> int:
        return getattr(self.inner, "dropped_spans", 0)


def synthetic_span(trace_id: str, name: str, start: float, end: float,
                   tags: Optional[dict] = None,
                   parent_id: Optional[str] = None) -> Span:
    """A fully-formed span from EXISTING timestamps (the device-dispatch /
    spill-hop / container spans ride stamps already taken on the hot path
    — building the span never reads a clock)."""
    return Span(trace_id=trace_id, span_id=secrets.token_hex(8),
                parent_id=parent_id, name=name, start=start, end=end,
                tags=dict(tags or {}))


class TraceStore:
    """Per-process tail-sampling trace store (one instance per process,
    like GLOBAL_WATERFALL — the balancer hook owns rendering and the
    admin read side)."""

    #: spans kept per pending trace (a runaway span producer must not
    #: grow one trace unboundedly)
    SPAN_CAP = 64
    #: bound on the pre-completion mark table (divergent/exemplar/forced
    #: flags noted before the verdict)
    MARK_CAP = 8192

    def __init__(self, config: Optional[TraceTailConfig] = None):
        self.config = config or tail_config()
        self.enabled = bool(self.config.enabled)
        self._lock = threading.Lock()
        #: trace_id -> [Span, ...] (insertion-ordered: first key is oldest)
        self._pending: Dict[str, List[Span]] = {}
        #: trace_id -> {reason, ...} noted before completion
        self._marks: Dict[str, set] = {}
        self._kept: SeqRingBuffer[dict] = SeqRingBuffer(
            max(8, int(self.config.keep_ring)))
        #: trace_id -> kept seq (consistent via the ring's evicted return)
        self._by_id: Dict[str, int] = {}
        self.kept_total: Dict[str, int] = {}
        self.dropped_total = 0
        self.pending_evicted = 0
        self._seen = 0
        floor = float(self.config.keep_floor)
        self._floor_every = int(round(1.0 / floor)) if floor > 0 else 0
        #: live tail threshold source (the balancer wires the waterfall's
        #: host-side p99 bucket here); the default is the SLO e2e target
        self.threshold_source: Optional[Callable[[], Optional[float]]] = None
        self.default_threshold_ms = 1000.0
        #: keep-time join: activation id -> flight-recorder placement
        #: digest (called ONLY for kept traces, never on the drop path)
        self.placement_lookup: Optional[Callable[[str], Optional[dict]]] = None
        self._attached: Optional[Tracer] = None

    @property
    def active(self) -> bool:
        """Enabled AND teed into a tracer — the gate the extra span sites
        (container pair, device dispatch, spill hop) check so processes
        without the plane never pay for span objects nobody collects."""
        return self.enabled and self._attached is not None

    # -- lifecycle ---------------------------------------------------------
    def attach(self, tracer: Optional[Tracer] = None) -> None:
        """Tee the tracer's reporter through this store. Idempotent —
        every balancer in the process attaches the same global store.
        Never called when disabled: the off state touches nothing."""
        if not self.enabled:
            return
        t = tracer if tracer is not None else GLOBAL_TRACER
        rep = t.reporter
        if isinstance(rep, _TeeReporter) and rep.store is self:
            return
        t.reporter = _TeeReporter(self, rep)
        self._attached = t

    def detach(self) -> None:
        """Restore the wrapped reporter (test isolation)."""
        t = self._attached
        if t is not None and isinstance(t.reporter, _TeeReporter) \
                and t.reporter.store is self:
            t.reporter = t.reporter.inner
        self._attached = None

    def reset(self) -> None:
        """Drop all state (bench riders isolate measured windows)."""
        with self._lock:
            self._pending.clear()
            self._marks.clear()
            self._kept = SeqRingBuffer(max(8, int(self.config.keep_ring)))
            self._by_id.clear()
            self.kept_total = {}
            self.dropped_total = 0
            self.pending_evicted = 0
            self._seen = 0

    # -- write side --------------------------------------------------------
    def _ingest(self, span: Span) -> None:
        """Reporter-tee entry: file the span under its trace id. Bounded:
        a new trace past `pending_limit` ages the oldest pending trace
        out (counted, never serialized). Dict ops are GIL-atomic — spans
        report from the event loop and worker threads alike."""
        tid = span.trace_id
        pend = self._pending
        spans = pend.get(tid)
        if spans is None:
            if len(pend) >= self.config.pending_limit:
                try:
                    old = next(iter(pend))
                    pend.pop(old, None)
                    self.pending_evicted += 1
                except (StopIteration, KeyError):
                    pass
            spans = pend[tid] = []
        if len(spans) < self.SPAN_CAP:
            spans.append(span)

    def emit(self, span: Span) -> None:
        """Report a pre-built (synthetic) span through the attached
        tracer's reporter, so it reaches both the tee and the sink."""
        t = self._attached if self._attached is not None else GLOBAL_TRACER
        t.reporter.report(span)

    def mark(self, trace_id: Optional[str], reason: str) -> None:
        """Note a keep reason BEFORE the verdict (divergent placement,
        pinned exemplar, explicit force flag). Consulted and consumed at
        completion."""
        if not self.enabled or not trace_id:
            return
        marks = self._marks
        s = marks.get(trace_id)
        if s is None:
            if len(marks) >= self.MARK_CAP:
                try:
                    marks.pop(next(iter(marks)), None)
                except (StopIteration, KeyError):
                    pass
            s = marks[trace_id] = set()
        s.add(reason)

    def force(self, trace_id: Optional[str], reason: str = "forced") -> None:
        """The explicit force-trace flag (and the exemplar pin hook)."""
        self.mark(trace_id, reason)

    # -- verdict -----------------------------------------------------------
    def tail_threshold_ms(self) -> float:
        src = self.threshold_source
        if src is not None:
            try:
                t = src()
                if t is not None:
                    return float(t)
            except Exception:  # noqa: BLE001 — a broken source must not
                pass           # take the completion path down
        return self.default_threshold_ms

    def complete(self, aid: str, trace_id: Optional[str],
                 e2e_ms: Optional[float] = None, *,
                 error: bool = False, timeout: bool = False,
                 forced: bool = False, fenced: bool = False,
                 row: Optional[dict] = None) -> Optional[dict]:
        """The completion-time tail-sampling verdict for one activation:
        decide keep/drop now that the outcome is known, and on keep join
        the pending spans with the waterfall row and the flight-recorder
        placement digest. Returns the kept entry, or None on drop."""
        if not self.enabled:
            return None
        tid = trace_id or (row.get("trace_id") if row else None)
        self._seen += 1
        marks = self._marks.pop(tid, None) if tid else None
        reasons: List[str] = []
        if error:
            reasons.append("error")
        if timeout or forced:
            reasons.append("timeout" if timeout else "forced")
        if fenced:
            reasons.append("fenced")
        if row is not None and row["deltas_us"][STAGE_SPILL_FORWARD] >= 0:
            reasons.append("spilled")
        if marks:
            for r in ("spilled", "forced", "divergent", "exemplar"):
                if r in marks and r not in reasons:
                    reasons.append(r)
        if e2e_ms is None and row is not None:
            e2e_ms = row["total_us"] / 1000.0
        if e2e_ms is not None and e2e_ms > self.tail_threshold_ms():
            reasons.append("slow")
        if not reasons and self._floor_every \
                and self._seen % self._floor_every == 0:
            reasons.append("floor")
        if not reasons:
            if tid:
                self._pending.pop(tid, None)
            self.dropped_total += 1
            return None
        # priority order for the counter label
        reasons.sort(key=REASONS.index)
        return self._keep(aid, tid, e2e_ms, reasons, row)

    def _keep(self, aid: str, tid: Optional[str], e2e_ms: Optional[float],
              reasons: List[str], row: Optional[dict]) -> dict:
        spans = self._pending.pop(tid, None) if tid else None
        placement = None
        if self.placement_lookup is not None:
            try:
                placement = self.placement_lookup(aid)
            except Exception:  # noqa: BLE001 — a join miss never drops
                placement = None
        entry = {
            "trace_id": tid,
            "activation_id": aid,
            "ts": row["ts"] if row else time.time(),
            "reason": reasons[0],
            "reasons": reasons,
            "e2e_ms": (round(e2e_ms, 3) if e2e_ms is not None else None),
            "identity": identity(),
            "spans": [s.to_json() for s in (spans or [])],
            "waterfall": dict(row) if row else None,
            "placement": placement,
            "quality": (placement or {}).get("quality"),
        }
        with self._lock:
            seq, evicted = self._kept.append(entry)
            entry["_seq"] = seq
            if evicted is not None:
                etid = evicted.get("trace_id")
                if etid and self._by_id.get(etid) == evicted.get("_seq"):
                    del self._by_id[etid]
            if tid:
                self._by_id[tid] = seq
            r = reasons[0]
            self.kept_total[r] = self.kept_total.get(r, 0) + 1
        return entry

    # -- read side ---------------------------------------------------------
    def get(self, trace_id: str) -> Optional[dict]:
        """The kept entry for one trace id, or None if never kept / the
        ring has wrapped past it."""
        with self._lock:
            seq = self._by_id.get(trace_id)
            if seq is None:
                return None
            entry = self._kept.get(seq)
        if entry is None or entry.get("trace_id") != trace_id:
            return None
        return entry

    def entries(self) -> List[dict]:
        """Every kept entry, oldest first (the loadgen NDJSON export)."""
        with self._lock:
            return list(self._kept.last(self._kept.size))

    def list(self, reason: Optional[str] = None, n: int = 50) -> List[dict]:
        """Kept-trace summaries, newest first, optionally filtered by
        verdict reason."""
        with self._lock:
            rows = self._kept.last(self._kept.size)
        out = []
        for e in reversed(rows):
            if reason and reason not in e["reasons"]:
                continue
            out.append({
                "trace_id": e["trace_id"],
                "activation_id": e["activation_id"],
                "ts": e["ts"],
                "reason": e["reason"],
                "reasons": e["reasons"],
                "e2e_ms": e["e2e_ms"],
                "spans": len(e["spans"]),
            })
            if len(out) >= n:
                break
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "identity": identity(),
                "pending": len(self._pending),
                "pending_evicted": self.pending_evicted,
                "kept": len(self._kept),
                "kept_total": dict(self.kept_total),
                "dropped_total": self.dropped_total,
                "seen": self._seen,
                "keep_floor": self.config.keep_floor,
                "tail_threshold_ms": self.tail_threshold_ms(),
            }

    # -- exposition --------------------------------------------------------
    def prometheus_text(self, openmetrics: bool = False) -> str:
        """`openwhisk_trace_kept_total{reason=...}` /
        `openwhisk_trace_dropped_total` (rendering shared with the other
        planes via controller/monitoring.py)."""
        if not self.enabled:
            return ""
        from ..controller.monitoring import counter_family_text
        with self._lock:
            kept = dict(self.kept_total)
            dropped = self.dropped_total
        out = counter_family_text(
            "openwhisk_trace_kept_total",
            [({"reason": r}, int(kept[r])) for r in sorted(kept)],
            openmetrics=openmetrics)
        # unlabeled counter: rendered bare (an empty `{}` label set is
        # invalid OpenMetrics), with the same TYPE-name rule as
        # counter_family_text (OM types the base, samples keep _total)
        drop = "openwhisk_trace_dropped_total"
        out += [f"# TYPE {drop[:-len('_total')] if openmetrics else drop} "
                "counter", f"{drop} {int(dropped)}"]
        return "\n".join(out)


# -- cross-process assembly -------------------------------------------------

def _stage_times_us(row: dict) -> Dict[int, int]:
    """Absolute stage times in µs since the row's own t0: the deltas
    telescope, so a running sum over PRESENT stages reconstructs each
    stamp's offset exactly."""
    out: Dict[int, int] = {}
    t = 0
    for i, d in enumerate(row.get("deltas_us") or []):
        if d < 0:
            continue
        t += d
        out[i] = t
    return out


def _half_key(half: dict) -> str:
    ident = half.get("identity") or {}
    inst = ident.get("instance")
    role = ident.get("role") or "proc"
    return f"{role}{inst if inst is not None else ''}" or "local"


def _pick_origin(halves: List[dict]) -> int:
    """The origin half: the one whose waterfall row starts the pipeline
    (api_accept present), else the longest row, else the first half."""
    best, best_score = 0, (-1, -1)
    for i, h in enumerate(halves):
        row = h.get("waterfall")
        if not row:
            continue
        times = _stage_times_us(row)
        score = (1 if 0 in times else 0, int(row.get("total_us") or 0))
        if score > best_score:
            best, best_score = i, score
    return best


def assemble_trace(trace_id: str, halves: List[dict],
                   members_missing: Iterable[Any] = ()) -> dict:
    """Merge per-process kept halves into ONE causal span tree.

    Alignment: the origin half's t0 is the tree's zero. A peer half with
    a spilled-in row pins its publish_enqueue to the origin's
    spill_forward stamp; an invoker-side half pins its invoker_pickup to
    the origin's publish_enqueue (both collapse bus transit into the
    handoff edge — the conservative alignment). Halves with neither
    handoff stamp fall back to wall-clock deltas between entry `ts`
    anchors. Spans are deduplicated by span id, so scraping the same
    process twice (or a shared in-process store) never double-counts.
    """
    seen = set()
    uniq: List[dict] = []
    for h in halves:
        if not h:
            continue
        # one half per process identity: scraping a shared in-process
        # store through three API servers yields three identical copies
        k = (_half_key(h), (h.get("identity") or {}).get("pid"),
             h.get("activation_id"), h.get("ts"))
        if k in seen:
            continue
        seen.add(k)
        uniq.append(h)
    halves = uniq
    if not halves:
        return {"trace_id": trace_id, "found": False,
                "members_missing": sorted(members_missing, key=str)}
    oi = _pick_origin(halves)
    origin = halves[oi]
    orow = origin.get("waterfall") or {}
    otimes = _stage_times_us(orow)
    ototal = int(orow.get("total_us") or 0)

    #: per-half offset (µs) of its own t0 on the origin timeline
    offsets: List[int] = []
    for i, h in enumerate(halves):
        if i == oi:
            offsets.append(0)
            continue
        row = h.get("waterfall") or {}
        times = _stage_times_us(row)
        if STAGE_SPILL_FORWARD in otimes and STAGE_PUBLISH_ENQUEUE in times:
            # spilled half: its enqueue IS the origin's spill handoff
            offsets.append(otimes[STAGE_SPILL_FORWARD]
                           - times[STAGE_PUBLISH_ENQUEUE])
        elif STAGE_PUBLISH_ENQUEUE in otimes and STAGE_INVOKER_PICKUP in times:
            offsets.append(otimes[STAGE_PUBLISH_ENQUEUE]
                           - times[STAGE_INVOKER_PICKUP])
        else:
            # wall-clock fallback: anchor completion timestamps
            o_ts, h_ts = origin.get("ts") or 0, h.get("ts") or 0
            h_total = int(row.get("total_us") or 0)
            offsets.append(int((h_ts - o_ts) * 1e6) + ototal - h_total)

    # -- collect nodes ------------------------------------------------------
    span_nodes: Dict[str, dict] = {}
    parent_of: Dict[str, Optional[str]] = {}
    groups: List[dict] = []
    procs: set = set()
    end_us = ototal

    for i, h in enumerate(halves):
        off = offsets[i]
        key = _half_key(h)
        procs.add(key)
        row = h.get("waterfall") or {}
        times = _stage_times_us(row)
        stage_nodes = []
        prev = 0
        placement = h.get("placement") or {}
        for si in sorted(times):
            start = prev + off
            dur = times[si] - prev
            node = {"name": f"stage:{STAGES[si]}", "proc": key,
                    "start_us": start, "duration_us": dur,
                    "tags": {}, "children": []}
            if STAGES[si] == "device_dispatch" and placement:
                # the per-micro-batch device link: the flight-recorder
                # digest joins this member to its batch (and the batch's
                # own span under the digest's trace id)
                node["tags"] = {
                    "batch_seq": placement.get("seq"),
                    "kernel": placement.get("kernel"),
                    "batch_trace_id": placement.get("trace_id"),
                }
            stage_nodes.append(node)
            prev = times[si]
            end_us = max(end_us, prev + off)
        group = {"name": f"proc:{key}", "proc": key,
                 "start_us": off, "duration_us": max(0, prev),
                 "tags": {}, "children": stage_nodes}
        groups.append(group)
        for sp in h.get("spans") or []:
            sid = sp.get("id")
            if not sid or sid in span_nodes:
                continue  # dedup across scraped copies of one store
            tags = sp.get("tags") or {}
            proc = tags.get("proc")
            if proc:
                procs.add(proc)
            # span wall µs -> origin-relative: anchor at the half's own
            # wall t0 (completion ts minus total), then shift by offset
            h_total = int(row.get("total_us") or 0)
            t0_wall_us = (h.get("ts") or 0) * 1e6 - h_total
            start = int(sp.get("timestamp", 0) - t0_wall_us) + off
            node = {"name": sp.get("name"), "proc": proc or key,
                    "start_us": start,
                    "duration_us": int(sp.get("duration") or 0),
                    "tags": tags, "children": []}
            span_nodes[sid] = node
            parent_of[sid] = sp.get("parentId")
            group.setdefault("_span_ids", []).append(sid)
            end_us = max(end_us, start + node["duration_us"])

    # -- link reported spans: parent when present, else the half group -----
    for sid, node in span_nodes.items():
        pid = parent_of.get(sid)
        if pid and pid in span_nodes:
            span_nodes[pid]["children"].append(node)
    for group in groups:
        for sid in group.pop("_span_ids", []):
            pid = parent_of.get(sid)
            if not (pid and pid in span_nodes):
                group["children"].append(span_nodes[sid])
        group["children"].sort(key=lambda n: n["start_us"])

    groups.sort(key=lambda g: g["start_us"])
    root = {"name": f"activation:{trace_id}", "proc": _half_key(origin),
            "start_us": 0, "duration_us": max(0, end_us),
            "tags": {"activation_id": origin.get("activation_id"),
                     "reason": origin.get("reason")},
            "children": groups}
    return {
        "trace_id": trace_id,
        "found": True,
        "e2e_us": root["duration_us"],
        "processes": sorted(procs),
        "reasons": sorted({r for h in halves
                           for r in (h.get("reasons") or [])}),
        "members_missing": sorted(members_missing, key=str),
        "root": root,
    }


#: the process-wide store (same pattern as GLOBAL_WATERFALL /
#: GLOBAL_TRACER): spans report from layers that share no balancer
#: reference; the CommonLoadBalancer hook attaches the tee, wires the
#: verdict and owns rendering + the admin read side
GLOBAL_TRACE_STORE = TraceStore()

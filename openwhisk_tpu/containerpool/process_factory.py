"""Process container driver: actions run in local subprocess sandboxes.

The reference's invoker shells out to the docker CLI to start runtime-image
containers (core/invoker/.../docker/DockerClient.scala:81-179). This driver
keeps the same Container contract but the sandbox is an OS subprocess running
the in-repo action proxy (openwhisk_tpu/containerpool/actionproxy.py) — the
natural container primitive for a single-host/TPU-pod deployment where docker
is unavailable. Pause/resume map to SIGSTOP/SIGCONT (the same mechanism runc
pause uses underneath); memory limits map to RLIMIT_AS.
"""
from __future__ import annotations

import asyncio
import os
import signal
import socket
import sys
import tempfile
import uuid
from typing import List, Optional

from ..core.entity import ByteSize
from .container import ACTIVATION_LOG_SENTINEL, Container, ContainerError
from .factory import ContainerFactory


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ProcessContainer(Container):
    def __init__(self, proc: asyncio.subprocess.Process, port: int,
                 stdout_path: str, stderr_path: str, kind: str, memory: ByteSize):
        super().__init__(f"proc-{proc.pid}-{uuid.uuid4().hex[:8]}", ("127.0.0.1", port))
        self.proc = proc
        self.stdout_path = stdout_path
        self.stderr_path = stderr_path
        self.kind = kind
        self.memory = memory
        self._log_offsets = {stdout_path: 0, stderr_path: 0}

    async def suspend(self) -> None:
        if self.proc.returncode is None:
            self.proc.send_signal(signal.SIGSTOP)

    async def resume(self) -> None:
        if self.proc.returncode is None:
            self.proc.send_signal(signal.SIGCONT)

    async def destroy(self) -> None:
        await super().destroy()
        if self.proc.returncode is None:
            self.proc.send_signal(signal.SIGCONT)  # can't reap a stopped proc
            self.proc.kill()
            try:
                await asyncio.wait_for(self.proc.wait(), 5)
            except asyncio.TimeoutError:
                pass
        for p in (self.stdout_path, self.stderr_path):
            try:
                os.unlink(p)
            except OSError:
                pass

    async def logs(self, limit_bytes: int = 10 * 1024 * 1024,
                   wait_for_sentinel: bool = True) -> List[str]:
        """Drain new log lines up to (and excluding) the sentinel on each
        stream (ref DockerToActivationLogStore semantics)."""
        out: List[str] = []
        for path in (self.stdout_path, self.stderr_path):
            stream = "stdout" if path == self.stdout_path else "stderr"
            lines = await self._read_stream(path, wait_for_sentinel)
            size = 0
            for line in lines:
                size += len(line)
                if size > limit_bytes:
                    out.append(f"{stream}: Logs were truncated because the total bytes size exceeds the limit")
                    break
                out.append(f"{stream}: {line}")
        return out

    async def _read_stream(self, path: str, wait_for_sentinel: bool,
                           timeout: float = 2.0) -> List[str]:
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            try:
                with open(path, "r", errors="replace") as f:
                    f.seek(self._log_offsets[path])
                    content = f.read()
            except OSError:
                return []
            if ACTIVATION_LOG_SENTINEL in content or not wait_for_sentinel:
                head, _, _ = content.partition(ACTIVATION_LOG_SENTINEL + "\n")
                if ACTIVATION_LOG_SENTINEL in content:
                    self._log_offsets[path] += len(head) + len(ACTIVATION_LOG_SENTINEL) + 1
                else:
                    self._log_offsets[path] += len(content)
                    head = content
                return [l for l in head.splitlines() if l]
            if asyncio.get_event_loop().time() > deadline:
                return [l for l in content.splitlines() if l]
            await asyncio.sleep(0.02)


class ProcessContainerFactory(ContainerFactory):
    def __init__(self, logger=None, max_parallel_creates: int = 16):
        self.logger = logger
        self._create_sem = asyncio.Semaphore(max_parallel_creates)
        self._containers: List[ProcessContainer] = []

    async def create_container(self, transid, name: str, image: str,
                               memory: ByteSize, cpu_shares: int = 0,
                               action=None) -> ProcessContainer:
        async with self._create_sem:
            port = _free_port()
            fd_out, stdout_path = tempfile.mkstemp(prefix=f"ow-{name}-", suffix=".out")
            fd_err, stderr_path = tempfile.mkstemp(prefix=f"ow-{name}-", suffix=".err")
            # memory cap is applied by the proxy itself after exec (a parent
            # preexec_fn would fork() a multithreaded JAX process, which can
            # deadlock the child before exec); leave interpreter headroom
            env = dict(os.environ,
                       OW_MEMORY_LIMIT_BYTES=str(memory.bytes + 512 * 1024 * 1024))
            # launch the proxy file directly (NOT -m): it is stdlib-only, so
            # this skips importing the parent package (aiohttp etc., ~2s)
            proxy_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                      "actionproxy.py")
            proc = await asyncio.create_subprocess_exec(
                sys.executable, "-u", proxy_path, str(port),
                stdout=fd_out, stderr=fd_err, start_new_session=True, env=env,
            )
            os.close(fd_out)
            os.close(fd_err)
            c = ProcessContainer(proc, port, stdout_path, stderr_path,
                                 kind=image, memory=memory)
            self._containers.append(c)
            return c

    async def cleanup(self) -> None:
        for c in list(self._containers):
            try:
                await c.destroy()
            except (ContainerError, OSError):
                pass
        self._containers.clear()


class ProcessContainerFactoryProvider:
    @staticmethod
    def instance(invoker_name: str = "invoker0", logger=None,
                 **kwargs) -> ProcessContainerFactory:
        # invoker_name is part of the uniform SPI signature; process
        # sandboxes are per-instance, so it carries no state here
        return ProcessContainerFactory(logger=logger, **kwargs)

from .container import (Container, ContainerError, InitializationError,
                        RunResult, ACTIVATION_LOG_SENTINEL)
from .factory import ContainerFactory, ContainerPoolConfig
from .process_factory import (ProcessContainer, ProcessContainerFactory,
                              ProcessContainerFactoryProvider)
from .docker_factory import DockerContainerFactory, docker_available
from .kubernetes_factory import (KubernetesClient, KubernetesClientConfig,
                                 KubernetesContainer,
                                 KubernetesContainerFactory, WhiskPodBuilder)
from .yarn_factory import YARNConfig, YARNContainerFactory
from .mesos_factory import MesosConfig, MesosContainerFactory
from .pool import ContainerPool, Run
from .proxy import ContainerProxy, ContainerData
from .logstore import ContainerLogStore, ContainerLogStoreProvider

__all__ = [n for n in dir() if not n.startswith("_")]

"""Coalescing producer: micro-batched bus produce behind the provider SPI.

The publish->dispatch->invoke->complete path used to pay one bus round trip
per activation: the balancer's readback fan-out wakes N publishers in one
event-loop sweep and each `await producer.send(...)` serialized on the
transport (one lock-guarded TCP frame + ack per message on the TCP bus; one
condition acquire + notify per message on the memory bus). Under open-loop
load those per-request costs compound into the tail (PAPERS.md: Dean &
Barroso — the cure is doing less serial work per request, amortized over
batches).

`CoalescingProducer` wraps any `MessageProducer` and turns concurrent sends
into micro-batches: a send enqueues (payload pre-serialized on the caller's
turn) and resolves when its batch's single `send_many` acknowledges. The
flush fires when the batch fills (`max_batch`) or when the oldest pending
message has waited `window_ms` (a Nagle-style bounded delay; `window_ms=0`
flushes at the end of the current event-loop sweep, which still coalesces a
whole readback wave). Flushes are serialized on one drainer task, so
per-producer ordering is exactly the serial producer's.

Backends with a native batch op ship one frame per micro-batch
(`TcpProducer.send_many` -> the broker's `pubN` op: one length-prefixed
frame, N payloads, one ack, broker-side dedupe per sub-message); backends
without one fall back to the base `send_many` (sequential sends — serial
semantics, no wire-protocol change).

Off switch: `CONFIG_whisk_bus_coalesce_enabled=false` makes
`maybe_coalesce()` return the raw producer — the serial path, bit-exact
with today's behavior.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..utils.config import load_config
from ..utils.microbatch import MicroCoalescer
from .connector import MessageProducer, encode_message

#: process-wide coalescing health counters, exported as gauges by the
#: balancers' supervision tick (export_coalesce_gauges) — one aggregate
#: across producers, like the tracing gauges
_STATS = {"batches": 0, "messages": 0, "max_batch": 0,
          "wire_batches": 0, "wire_batched_messages": 0}


@dataclass(frozen=True)
class BusCoalesceConfig:
    """`CONFIG_whisk_bus_coalesce_*` env overrides."""
    enabled: bool = True
    #: flush as soon as this many messages are pending
    max_batch: int = 64
    #: bounded accumulation delay: the oldest pending message waits at most
    #: this long before its frame ships. Default 0 = flush at the end of
    #: the current event-loop sweep, which already coalesces a whole
    #: readback/ack wave at ZERO added idle latency (measured: the produce
    #: stage p99 stays ~1 ms at the sustained rate). Set ~1 ms on expensive
    #: transports (remote TCP, Kafka) to also batch across waves.
    window_ms: float = 0.0
    #: columnar batch wire (messaging/columnar.py): same-topic
    #: activation/ack messages in one flush ship as ONE encoded batch
    #: record — one json.dumps per batch with per-batch identity/action
    #: dedup, instead of N independent encodes (and the encode moves to
    #: flush time, so a message serialized for a batch is encoded exactly
    #: once). False restores the serial wire format byte-exactly.
    batch_wire: bool = True
    #: lazy ack result column (ISSUE 14): ack batch frames carry each
    #: activation's response payload as an opaque bytes column after the
    #: JSON header, so the controller's completion loop never parses a
    #: result nobody reads (blocking invokes parse on the API turn;
    #: fire-and-forget acks skip the parse entirely). False restores the
    #: PR 11 ack batch record byte-exactly.
    lazy_results: bool = True

    @classmethod
    def from_env(cls) -> "BusCoalesceConfig":
        return load_config(cls, env_path="bus.coalesce")


class CoalescingProducer(MessageProducer):
    """Micro-batching wrapper over any MessageProducer (see module doc).
    The coalescing loop itself is the shared MicroCoalescer
    (utils/microbatch.py) — the admission plane rides the same one."""

    def __init__(self, inner: MessageProducer, max_batch: int = 64,
                 window_ms: float = 0.0, batch_wire: bool = False,
                 lazy_results: bool = False):
        self.inner = inner
        self.batch_wire = batch_wire
        self.lazy_results = lazy_results
        self._co = MicroCoalescer(self._ship, max_batch,
                                  max(0.0, float(window_ms)) / 1e3,
                                  name="bus-coalesce-drain")

    @property
    def sent_count(self) -> int:
        return self.inner.sent_count

    @property
    def pending_count(self) -> int:
        return self._co.pending_count

    async def send(self, topic: str, msg) -> None:
        # Batch wire fast path: a batchable message (activation / ack) is
        # NOT encoded here — it rides to the flush as an object and is
        # encoded exactly once, inside its batch's single json.dumps.
        # Everything else serializes on the caller's turn as before (the
        # flush loop then ships bytes without touching message objects,
        # and a slow .serialize() is charged to the sender, not to every
        # batch-mate). encode_message / encode_batch both feed the host
        # observatory's per-hop serde accounting.
        if self.batch_wire and not isinstance(msg, (bytes, bytearray)):
            from .columnar import batchable_family
            family = batchable_family(msg)
            if family is not None:
                await self._co.submit((topic, family, msg))
                return
        payload = encode_message(msg)
        await self._co.submit((topic, payload, msg))

    def send_nowait(self, topic: str, msg) -> "asyncio.Future":
        """Public task-free submit (the batched publish SPI resolves its
        callers from this future's done-callback): enqueue now, flush
        with the coalescer's next drain."""
        return self._submit_nowait(topic, msg)

    def _submit_nowait(self, topic: str, msg) -> "asyncio.Future":
        """send() without the await: enqueue, return the flush future."""
        if self.batch_wire and not isinstance(msg, (bytes, bytearray)):
            from .columnar import batchable_family
            family = batchable_family(msg)
            if family is not None:
                return self._co.submit_nowait((topic, family, msg))
        return self._co.submit_nowait((topic, encode_message(msg), msg))

    async def send_batch(self, topic: str, msgs: list) -> None:
        """Submit a whole wave in one sweep and await the flush ONCE: the
        per-item futures resolve together (same per-item error
        propagation as N send() calls) with no task per message —
        `asyncio.gather` over coroutines would mint one Task each, which
        at thousands of acks/s was measurable loop churn. Failures
        gather with return_exceptions so sibling futures are all
        retrieved (no unretrieved-exception log spam), then the first
        real failure raises."""
        import asyncio
        futs = [self._submit_nowait(topic, m) for m in msgs]
        results = await asyncio.gather(*futs, return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                raise r

    async def _ship(self, batch) -> None:
        """One coalesced flush: the whole batch rides the provider's
        send_many (one pubN frame on the TCP bus). With the batch wire
        on, same-topic batchable messages collapse into ONE columnar
        record per (topic, family) — encoded here, exactly once per
        message — so the pubN frame carries one payload per topic
        instead of one per message. The coalescer resolves the waiter
        futures on return / failure."""
        _STATS["batches"] += 1
        _STATS["messages"] += len(batch)
        _STATS["max_batch"] = max(_STATS["max_batch"], len(batch))
        if not self.batch_wire:
            await self.inner.send_many([item for (item, _fut) in batch])
            return
        from .connector import encode_batch
        # group deferred-encode messages per (topic, family), preserving
        # per-topic arrival order WITHIN a family (the serial ordering
        # contract is per-topic; cross-topic order was never guaranteed —
        # send_many already interleaves topics). Pre-encoded items pass
        # through at their arrival position. Caveat, by design: a topic
        # carrying BOTH batchable and unbatchable payloads in one flush
        # may reorder across the kinds (the group anchors at its first
        # message) — no shipped topic mixes kinds (invoker topics carry
        # activations, completed* topics carry acks, health/events stay
        # per-frame), and consumers of each kind are order-independent
        # across the other.
        items: list = []
        groups: dict = {}
        for (topic, payload_or_family, msg), fut in batch:
            if isinstance(payload_or_family, str):
                key = (topic, payload_or_family)
                grp = groups.get(key)
                if grp is None:
                    grp = groups[key] = []
                    # placeholder keeps this group's position in the
                    # flush order (first appearance of the topic)
                    items.append(key)
                grp.append((msg, fut))
            else:
                items.append((topic, payload_or_family, msg))
        out: list = []
        for it in items:
            if isinstance(it, tuple) and len(it) == 2:
                topic, family = it
                group = groups[(topic, family)]
                msgs = [m for (m, _f) in group]
                if len(msgs) == 1:
                    # a lone message pays the plain wire format — the
                    # decode side needs no batch frame for N=1 and the
                    # serial consumers stay compatible
                    try:
                        out.append((topic, encode_message(msgs[0]),
                                    msgs[0]))
                    except Exception as e:  # noqa: BLE001
                        self._fail_group(group, e)
                    continue
                try:
                    payload, batch_msg = encode_batch(
                        family, msgs, lazy_results=self.lazy_results)
                except Exception:  # noqa: BLE001 — deferring the encode
                    # to flush time must NOT widen one bad message's
                    # blast radius to the whole flush (the serial path
                    # charged a serialize failure to its sender): retry
                    # each message alone so only the unserializable ones
                    # fail, and the rest still ship
                    for m, fut in group:
                        try:
                            out.append((topic, encode_message(m), m))
                        except Exception as e:  # noqa: BLE001
                            if not fut.done():
                                fut.set_exception(e)
                    continue
                _STATS["wire_batches"] += 1
                _STATS["wire_batched_messages"] += len(msgs)
                out.append((topic, payload, batch_msg))
            else:
                out.append(it)
        await self.inner.send_many(out)

    @staticmethod
    def _fail_group(group, exc) -> None:
        for _m, fut in group:
            if not fut.done():
                fut.set_exception(exc)

    async def flush(self) -> None:
        """Wait until everything enqueued so far has shipped (or failed)."""
        await self._co.drain_all()

    async def close(self) -> None:
        await self.flush()
        await self.inner.close()


def maybe_coalesce(producer: MessageProducer,
                   config: Optional[BusCoalesceConfig] = None
                   ) -> MessageProducer:
    """The wiring hook for producer owners (balancer, invoker, bench echo
    fleet): wrap in a CoalescingProducer when coalescing is on; hand back
    the raw producer — the bit-exact serial path — when it is off."""
    cfg = config if config is not None else BusCoalesceConfig.from_env()
    if not cfg.enabled or isinstance(producer, CoalescingProducer):
        return producer
    return CoalescingProducer(producer, cfg.max_batch, cfg.window_ms,
                              batch_wire=cfg.batch_wire,
                              lazy_results=cfg.lazy_results)


def export_coalesce_gauges(metrics) -> None:
    """Coalescing health gauges (ridden by the balancers' supervision tick,
    like export_tracing_gauges): flushed batch/message counts and the
    largest batch seen — messages/batches is the live amortization factor."""
    metrics.gauge("bus_coalesce_batches", _STATS["batches"])
    metrics.gauge("bus_coalesce_messages", _STATS["messages"])
    metrics.gauge("bus_coalesce_batch_max", _STATS["max_batch"])
    metrics.gauge("bus_wire_batches", _STATS["wire_batches"])
    metrics.gauge("bus_wire_batched_messages",
                  _STATS["wire_batched_messages"])

"""ISSUE 20: front-end -> balancer admission funnel, tier-1 half.

Covers the acceptance contracts:
  * wire roundtrips for the `fun1` admission frame (act1 columns +
    origin/seq/epoch header) and the `funA` per-row outcome frame;
  * partial-dedupe replay over the REAL TCP bus: a retried frame places
    only rows whose first delivery was lost — zero double executions;
  * fence-stamped rows refused whole by a stale-epoch balancer (both
    failure directions: zombie sender behind, demoted balancer behind),
    with the refusal text naming both epochs;
  * backpressure 429 text parity: the funnel-depth bound answers with
    the serial front door's EXACT CONCURRENT_LIMIT_MESSAGE, and the
    device-rate throttle's exact serial text + exception type survive
    the wire hop;
  * blocking completion roundtrip: the front end's promise resolves to
    the WhiskActivation placed at the balancer;
  * the sender's application-level retry re-ships lost frames and the
    receiver's outcome cache answers replayed rows from memory.

The multi-process shared-deployment sweep rides the `multiproc` marker
(conftest probe: cpu count + spawn capability).
"""
from __future__ import annotations

import asyncio
import time

import pytest

from openwhisk_tpu.controller.entitlement import CONCURRENT_LIMIT_MESSAGE
from openwhisk_tpu.controller.loadbalancer.base import (
    ActiveAckTimeout, LoadBalancerException, LoadBalancerThrottleException)
from openwhisk_tpu.controller.loadbalancer.funnel import (
    FrameSender, FunnelBalancer, FunnelConfig, FunnelReceiver,
    funnel_ack_topic, funnel_topic, stale_epoch_text)
from openwhisk_tpu.core.entity import (ActivationId, ActivationResponse,
                                       ControllerInstanceId, EntityName,
                                       EntityPath, Identity, Subject,
                                       WhiskActivation)
from openwhisk_tpu.messaging import MemoryMessagingProvider
from openwhisk_tpu.messaging.columnar import (FunnelAckMessage,
                                              FunnelBatchMessage,
                                              FunnelOutcome, KIND_FUNNEL,
                                              KIND_FUNNEL_ACK,
                                              is_batch_payload, parse_batch)

from tests.test_balancers import make_action, make_msg
from tests.test_partitions import until

DEVICE_THROTTLE_TEXT = ("Too many requests in the last minute "
                        "(device rate admission).")


def _activation(aid: ActivationId) -> WhiskActivation:
    now = int(time.time() * 1000)
    return WhiskActivation(EntityPath("guest"), EntityName("fx"),
                           Subject("guest-user"), aid, now, now,
                           ActivationResponse.success({"ok": 1}),
                           duration=1)


class StubBalancer:
    """A balancer double implementing the publish_many contract: each
    row future resolves to a completion promise (mode='place'), raises
    the serial device throttle ('throttle') or the standby refusal
    ('standby'). Placements are recorded so double executions show."""

    fence_epoch = None
    waterfall = None

    def __init__(self, mode="place"):
        self.mode = mode
        self.placed = []
        self.promises = {}

    def publish_many(self, pairs):
        loop = asyncio.get_event_loop()
        outs = []
        for _action, msg in pairs:
            out = loop.create_future()
            aid = msg.activation_id.asString
            if self.mode == "throttle":
                out.set_exception(
                    LoadBalancerThrottleException(DEVICE_THROTTLE_TEXT))
            elif self.mode == "standby":
                out.set_exception(LoadBalancerException(
                    "standby controller: placement is fenced to the "
                    "active leader"))
            else:
                self.placed.append(aid)
                promise = loop.create_future()
                self.promises[aid] = promise
                out.set_result(promise)
            outs.append(out)
        return outs


async def _resolver(name, rev):
    return make_action("fx", memory=128)


def _receiver(provider, balancer, instance="0", **kw):
    return FunnelReceiver(provider, ControllerInstanceId(instance),
                          balancer, resolver=_resolver, **kw)


def _frontend(provider, origin="7", target=0, **cfg):
    config = FunnelConfig(**cfg) if cfg else FunnelConfig()
    return FunnelBalancer(provider, ControllerInstanceId(origin),
                          target=target, config=config)


def _msgs(n, blocking=False):
    action = make_action("fx", memory=128)
    ident = Identity.generate("guest")
    return action, [make_msg(action, ident, blocking) for _ in range(n)]


class TestFunnelWire:
    def test_funnel_frame_roundtrip(self):
        action, msgs = _msgs(3, blocking=True)
        frame = FunnelBatchMessage(msgs, origin=7, seq=42, epoch=5)
        raw = frame.serialize()
        assert is_batch_payload(raw)
        kind, decoded = parse_batch(raw)
        assert kind == KIND_FUNNEL
        assert (decoded.origin, decoded.seq, decoded.epoch) == (7, 42, 5)
        assert [m.activation_id.asString for m in decoded.msgs] == \
            [m.activation_id.asString for m in msgs]
        for orig, back in zip(msgs, decoded.msgs):
            assert str(back.action) == str(orig.action)
            assert back.blocking == orig.blocking
            assert back.user.subject == orig.user.subject

    def test_funnel_ack_roundtrip_all_codes(self):
        aid = ActivationId.generate()
        act = _activation(aid)
        rows = [
            FunnelOutcome("p", "a1"),
            FunnelOutcome("r", "a2", exc=("T", DEVICE_THROTTLE_TEXT)),
            FunnelOutcome("r", "a3", exc=("L", "no invokers")),
            FunnelOutcome("c", aid.asString, resp=act.to_json()),
            FunnelOutcome("c", "a5"),  # slim non-blocking completion
            FunnelOutcome("f", "a6", err=True),
        ]
        raw = FunnelAckMessage(7, 3, rows).serialize()
        assert is_batch_payload(raw)
        kind, frame = parse_batch(raw)
        assert kind == KIND_FUNNEL_ACK
        assert (frame.origin, frame.epoch) == (7, 3)
        assert [r.code for r in frame.rows] == list("prrccf")
        assert frame.rows[1].exc == ("T", DEVICE_THROTTLE_TEXT)
        assert frame.rows[2].exc == ("L", "no invokers")
        back = WhiskActivation.from_json(frame.rows[3].resp)
        assert back.activation_id.asString == aid.asString
        assert frame.rows[4].resp is None
        assert frame.rows[5].err is True


class TestFunnelReceiver:
    def test_partial_dedupe_replay(self):
        """The pubN discipline one layer up: a replayed frame (same
        seq) places ONLY rows never seen — zero double executions."""

        async def go():
            provider = MemoryMessagingProvider()
            bal = StubBalancer()
            recv = _receiver(provider, bal)
            recv.start()
            producer = provider.get_producer()
            action, msgs = _msgs(3)
            a, b, c = msgs
            await producer.send(funnel_topic(0),
                                FunnelBatchMessage([a, b], 7, 1, 0))
            await until(lambda: len(bal.placed) == 2)
            # replay seq 1 with one extra row: only C is fresh
            await producer.send(funnel_topic(0),
                                FunnelBatchMessage([a, b, c], 7, 1, 0))
            await until(lambda: len(bal.placed) == 3)
            await asyncio.sleep(0.05)
            placed, dups = list(bal.placed), recv.dup_rows
            await recv.stop()
            return placed, dups, [m.activation_id.asString for m in msgs]

        placed, dups, aids = asyncio.run(go())
        assert placed == aids, "every row places exactly once, in order"
        assert dups == 2

    def test_stale_epoch_refuses_whole_frame(self):
        """Nonzero frame epochs must equal the balancer's live epoch:
        a frame behind (zombie sender) and a frame ahead (demoted,
        stale-epoch balancer) are both refused whole, naming both
        epochs. Epoch 0 = unfenced bootstrap, admitted."""

        async def go():
            provider = MemoryMessagingProvider()
            bal = StubBalancer()
            bal.fence_epoch = 3
            recv = _receiver(provider, bal)
            recv.start()
            acks = []
            consumer = provider.get_consumer(funnel_ack_topic(7), "t")
            producer = provider.get_producer()

            async def drain():
                while True:
                    for _t, _p, _o, payload in await consumer.peek(
                            16, timeout=0.05):
                        _kind, frame = parse_batch(payload)
                        acks.extend(frame.rows)
                    consumer.commit()
                    await asyncio.sleep(0.01)

            drainer = asyncio.get_event_loop().create_task(drain())
            action, msgs = _msgs(4)
            # frame behind the balancer: zombie sender
            await producer.send(funnel_topic(0),
                                FunnelBatchMessage(msgs[:2], 7, 1, 2))
            # frame ahead of the balancer: this balancer is stale
            await producer.send(funnel_topic(0),
                                FunnelBatchMessage(msgs[2:3], 7, 2, 4))
            await until(lambda: len(acks) >= 3)
            # at the live epoch: admitted
            await producer.send(funnel_topic(0),
                                FunnelBatchMessage(msgs[3:], 7, 3, 3))
            await until(lambda: len(bal.placed) == 1)
            await asyncio.sleep(0.05)
            drainer.cancel()
            out = (list(bal.placed), list(acks), recv.stale_frames)
            await recv.stop()
            return out

        placed, acks, stale = asyncio.run(go())
        assert len(placed) == 1, "only the live-epoch frame placed"
        assert stale == 2
        refusals = [r for r in acks if r.code == "r"]
        assert len(refusals) == 3
        texts = {r.exc[1] for r in refusals}
        assert stale_epoch_text(2, 3) in texts
        assert stale_epoch_text(4, 3) in texts
        assert all(r.exc[0] == "L" for r in refusals)


class TestFunnelFrontEnd:
    def test_backpressure_429_exact_serial_text(self):
        """The funnel-depth bound IS the front door's 429: the exact
        serial CONCURRENT_LIMIT_MESSAGE, raised immediately — never
        unbounded queueing."""

        async def go():
            provider = MemoryMessagingProvider()
            fe = _frontend(provider, depth=2)
            await fe.start()
            action, msgs = _msgs(3)
            outs = fe.publish_many([(action, m) for m in msgs])
            # depth 2: the third row refuses locally, at once
            assert outs[2].done()
            with pytest.raises(LoadBalancerThrottleException) as ei:
                outs[2].result()
            text = str(ei.value)
            await fe.close()
            return text

        text = asyncio.run(go())
        assert text == CONCURRENT_LIMIT_MESSAGE

    def _run_hop(self, mode, blocking=True, n=1):
        """One front end + one receiver over a shared provider; returns
        (row outcomes or exceptions, stub balancer, front end)."""

        async def go():
            provider = MemoryMessagingProvider()
            bal = StubBalancer(mode)
            recv = _receiver(provider, bal)
            recv.start()
            fe = _frontend(provider)
            await fe.start()
            action, msgs = _msgs(n, blocking=blocking)
            outs = fe.publish_many([(action, m) for m in msgs])
            results = []
            for out, m in zip(outs, msgs):
                try:
                    promise = await asyncio.wait_for(out, 8)
                except Exception as e:  # noqa: BLE001 — the assertion
                    results.append(e)
                    continue
                if mode == "place":
                    aid = m.activation_id.asString
                    await until(lambda a=aid: a in bal.promises)
                    bal.promises[aid].set_result(_activation(
                        m.activation_id))
                try:
                    results.append(await asyncio.wait_for(promise, 8))
                except Exception as e:  # noqa: BLE001
                    results.append(e)
            stats = (fe.rows_sent, fe.total_active_activations,
                     recv.rows_received)
            await fe.close()
            await recv.stop()
            return results, stats

        return asyncio.run(go())

    def test_device_throttle_text_survives_hop(self):
        results, _ = self._run_hop("throttle")
        (exc,) = results
        assert isinstance(exc, LoadBalancerThrottleException)
        assert str(exc) == DEVICE_THROTTLE_TEXT

    def test_standby_refusal_text_survives_hop(self):
        results, _ = self._run_hop("standby")
        (exc,) = results
        assert isinstance(exc, LoadBalancerException)
        assert not isinstance(exc, LoadBalancerThrottleException)
        assert str(exc) == ("standby controller: placement is fenced to "
                            "the active leader")

    def test_blocking_completion_roundtrip(self):
        results, stats = self._run_hop("place", blocking=True, n=3)
        assert len(results) == 3
        for act in results:
            assert isinstance(act, WhiskActivation)
            assert act.response.result == {"ok": 1}
        rows_sent, in_flight, rows_received = stats
        assert rows_sent == 3 and rows_received == 3
        assert in_flight == 0, "completed rows left the depth books"

    def test_retry_reships_lost_frame_no_double_execution(self):
        """Drop the first delivery: the sender re-ships the same seq
        after retry_seconds; rows place exactly once."""

        async def go():
            provider = MemoryMessagingProvider()
            bal = StubBalancer()
            recv = _receiver(provider, bal)
            dropped = []
            orig_consume = recv._consume

            async def lossy(payload):
                if not dropped:
                    dropped.append(payload)
                    return  # lose the first frame entirely
                await orig_consume(payload)

            recv._consume = lossy
            recv.start()
            fe = _frontend(provider, depth=64, retry_seconds=0.15,
                           max_retries=3)
            await fe.start()
            action, msgs = _msgs(2, blocking=True)
            outs = fe.publish_many([(action, m) for m in msgs])
            promises = await asyncio.wait_for(
                asyncio.gather(*outs), 8)
            for m in msgs:
                bal.promises[m.activation_id.asString].set_result(
                    _activation(m.activation_id))
            acts = await asyncio.wait_for(asyncio.gather(*promises), 8)
            out = (list(bal.placed), fe.frame_retries, len(dropped),
                   [a.activation_id.asString for a in acts])
            await fe.close()
            await recv.stop()
            return out

        placed, retries, dropped, aids = asyncio.run(go())
        assert dropped == 1 and retries >= 1
        assert sorted(placed) == sorted(aids)
        assert len(placed) == len(set(placed)) == 2, \
            "zero double executions across the retry"

    def test_retry_exhaustion_fails_rows_503(self):
        async def go():
            provider = MemoryMessagingProvider()
            # no receiver at all: every send vanishes
            fe = _frontend(provider, depth=8, retry_seconds=0.05,
                           max_retries=1)
            await fe.start()
            action, msgs = _msgs(1)
            (out,) = fe.publish_many([(action, msgs[0])])
            with pytest.raises(LoadBalancerException) as ei:
                await asyncio.wait_for(out, 8)
            text = str(ei.value)
            stats = (fe.rows_timed_out, fe.total_active_activations)
            await fe.close()
            return text, stats

        text, (timed_out, in_flight) = asyncio.run(go())
        assert "no outcome from balancer" in text
        assert timed_out == 1 and in_flight == 0

    def test_forced_timeout_surfaces_as_active_ack_timeout(self):
        async def go():
            provider = MemoryMessagingProvider()
            bal = StubBalancer()
            recv = _receiver(provider, bal)
            recv.start()
            fe = _frontend(provider)
            await fe.start()
            action, msgs = _msgs(1, blocking=True)
            (out,) = fe.publish_many([(action, msgs[0])])
            promise = await asyncio.wait_for(out, 8)
            aid = msgs[0].activation_id.asString
            await until(lambda: aid in bal.promises)
            # the balancer's forced completion path sets ActiveAckTimeout
            bal.promises[aid].set_exception(
                ActiveAckTimeout(msgs[0].activation_id))
            with pytest.raises(ActiveAckTimeout):
                await asyncio.wait_for(promise, 8)
            await fe.close()
            await recv.stop()
            return True

        assert asyncio.run(go())


class TestFunnelOverTcpBus:
    def test_partial_dedupe_replay_over_tcp(self):
        """Satellite: the dedupe/retry discipline over the REAL TCP
        bus — a lossy receiver forces an application-level re-ship and
        every row still executes exactly once, with the serial throttle
        text intact for a refused row."""

        async def go():
            from openwhisk_tpu.messaging.tcp import (TcpBusServer,
                                                     TcpMessagingProvider)
            import socket
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                port = s.getsockname()[1]
            server = TcpBusServer("127.0.0.1", port)
            await server.start()
            try:
                recv_provider = TcpMessagingProvider("127.0.0.1", port)
                send_provider = TcpMessagingProvider("127.0.0.1", port)
                bal = StubBalancer()
                recv = _receiver(recv_provider, bal)
                dropped = []
                orig_consume = recv._consume

                async def lossy(payload):
                    if not dropped:
                        dropped.append(payload)
                        return
                    await orig_consume(payload)

                recv._consume = lossy
                recv.start()
                fe = _frontend(send_provider, depth=64,
                               retry_seconds=0.2, max_retries=4)
                await fe.start()
                action, msgs = _msgs(3, blocking=True)
                outs = fe.publish_many([(action, m) for m in msgs])
                promises = await asyncio.wait_for(
                    asyncio.gather(*outs), 15)
                for m in msgs:
                    aid = m.activation_id.asString
                    await until(lambda a=aid: a in bal.promises)
                    bal.promises[aid].set_result(
                        _activation(m.activation_id))
                acts = await asyncio.wait_for(
                    asyncio.gather(*promises), 15)
                placed = list(bal.placed)
                retries = fe.frame_retries
                await fe.close()
                await recv.stop()
                return placed, retries, len(acts)
            finally:
                await server.stop()

        placed, retries, n_acts = asyncio.run(go())
        assert retries >= 1, "the lost frame was re-shipped"
        assert len(placed) == len(set(placed)) == 3, \
            "zero double executions over the TCP hop"
        assert n_acts == 3


@pytest.mark.multiproc
class TestFunnelSharedDeployment:
    def test_loadgen_shared_topology_end_to_end(self):
        """Two loadgen worker PROCESSES funnel one shared balancer
        process over the TCP bus; the merged verdict is tagged
        topology='shared' and every worker completes work."""
        import sys
        sys.path.insert(0, "tools")
        try:
            import loadgen
            out = loadgen.multiproc_fixed_rate(
                rate=48, procs=2, duration=1.0, n_invokers=2,
                shared=True, p99_bound_ms=60000.0)
        finally:
            sys.path.remove("tools")
        assert out["topology"] == "shared"
        assert out["mode"] == "open_loop_multiproc"
        assert out["completed"] > 0, "merged sample union is non-empty"
        assert out["fleet_merged_sustained_per_sec"] > 0
        assert len(out["per_worker"]) == 2
        for w in out["per_worker"]:
            assert "error" not in w, w
            assert (w.get("throughput_per_sec") or 0) > 0
